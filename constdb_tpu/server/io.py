"""TCP server: RESP client connections + replica handshake + cron.

Capability parity with the reference's accept loop / Link scheduling / cron
(reference src/server.rs:94-146, src/link.rs), mapped onto one asyncio
event loop: the loop is the single-writer exec thread (the reference's main
thread, server.rs:128-131); per-connection coroutines are its IO threads.
Parsing happens in the connection coroutine, execution inline — the mpsc
hand-off the reference needs between thread pools simply disappears.

A client connection that sends `SYNC` is upgraded to a replica link
(reference replica.rs:16-40: sync_command steals the client's Conn)."""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Optional

from ..errors import CstError
from ..replica.link import ReplicaLink, SYNC
from ..replica.manager import ReplicaManager, ReplicaMeta
from ..resp.codec import RespParser, encode_into, make_parser
from ..resp.message import Arr, Bulk, Err, Int, NoReply, as_bytes, as_int
from .node import Node

log = logging.getLogger(__name__)

_READ_CHUNK = 1 << 16


class ServerApp:
    """One node's process: listener, replica links, cron, config knobs."""

    def __init__(self, node: Node, host: str = "127.0.0.1", port: int = 0,
                 advertised_addr: str = "", work_dir: str = ".",
                 heartbeat: float = 4.0,
                 reconnect_delay: Optional[float] = None,
                 reconnect_max: Optional[float] = None,
                 reconnect_factor: Optional[float] = None,
                 reconnect_jitter: Optional[float] = None,
                 handshake_timeout: float = 10.0,
                 snapshot_chunk_keys: int = 1 << 16,
                 snapshot_compress_level: int = 1,
                 gc_interval: float = 1.0,
                 snapshot_path: str = "",
                 sync_merge_group: int = 8,
                 sync_merge_budget: float = 0.1,
                 sync_initial_split: int = 1024,
                 tcp_backlog: int = 1024,
                 gc_peer_retention: float = 0.0,
                 ingest_shards: int = 0,
                 ingest_shard_min_bytes: int = 64 << 20,
                 apply_batch: Optional[int] = None,
                 apply_latency: Optional[float] = None,
                 wire_batch: Optional[int] = None,
                 wire_latency: Optional[float] = None,
                 wire_compress: Optional[bool] = None,
                 wire_compress_min: Optional[int] = None,
                 encode_cache_mb: Optional[int] = None,
                 bulk_compress_level: int = 6,
                 serve_batch: Optional[int] = None,
                 serve_shards: Optional[int] = None,
                 delta_sync: Optional[bool] = None,
                 delta_max_divergence: Optional[float] = None,
                 delta_bucket_keys: Optional[int] = None,
                 delta_stamp_min: Optional[int] = None,
                 maxmemory: Optional[int] = None,
                 maxmemory_soft_pct: Optional[float] = None,
                 client_outbuf_max: Optional[int] = None,
                 repl_window: Optional[int] = None,
                 aof: Optional[bool] = None,
                 aof_fsync: Optional[str] = None,
                 aof_rewrite_pct: Optional[int] = None,
                 aof_rewrite_min_mb: Optional[int] = None,
                 aof_dir: str = "",
                 checkpoint_secs: Optional[float] = None,
                 checkpoint_min_mb: Optional[int] = None,
                 restore_to: int = 0,
                 cluster: Optional[bool] = None,
                 cluster_group: int = 0,
                 slot_groups: Optional[int] = None,
                 migrate_batch_mb: Optional[int] = None):
        self.node = node
        node.app = self
        if node.replicas is None:
            node.replicas = ReplicaManager()
        node.replicas.on_new_peer = self.ensure_link
        self.host = host
        self.port = port
        self._advertised = advertised_addr
        self.work_dir = work_dir
        self.heartbeat = heartbeat
        # replica-link reconnect: bounded exponential backoff with
        # DETERMINISTIC jitter (replica/link.py backoff_delay) — base
        # delay, ceiling, growth factor, jitter fraction.  None = the
        # CONSTDB_RECONNECT_* env defaults.  The jitter derives from
        # (node_id, peer addr, attempt) instead of random(), so a chaos
        # scenario's reconnect cadence replays exactly from its seed.
        from ..conf import env_float as _envf
        self.reconnect_delay = _envf("CONSTDB_RECONNECT_BASE_MS",
                                     5000.0) / 1000.0 \
            if reconnect_delay is None else reconnect_delay
        self.reconnect_max = _envf("CONSTDB_RECONNECT_MAX_MS",
                                   60000.0) / 1000.0 \
            if reconnect_max is None else reconnect_max
        self.reconnect_factor = _envf("CONSTDB_RECONNECT_FACTOR", 2.0) \
            if reconnect_factor is None else reconnect_factor
        self.reconnect_jitter = _envf("CONSTDB_RECONNECT_JITTER", 0.2) \
            if reconnect_jitter is None else reconnect_jitter
        # the seam the chaos harness (constdb_tpu/chaos) installs to
        # route EVERY inter-node transport through its fault plane: an
        # async callable (host, port) -> (reader, writer).  None = a
        # plain TCP connection.  Replica links are always the DIALING
        # side of their connection (an inbound SYNC adopts a stream some
        # peer's link dialed), so wrapping dials covers the whole mesh.
        self.peer_connector = None
        self.handshake_timeout = handshake_timeout
        self.snapshot_chunk_keys = snapshot_chunk_keys
        self.snapshot_compress_level = snapshot_compress_level
        self.gc_interval = gc_interval
        self.snapshot_path = snapshot_path
        # snapshot-apply cadence: chunks per engine call (ceiling), the
        # per-call liveness budget (seconds) the adaptive controller steers
        # toward, and the sub-chunk size the ramp starts from.  The start
        # must be small enough that the FIRST call cannot wedge the loop
        # even through the per-row CPU engine on a slow box (~10k keys/s
        # single-core: 1024 keys ≈ 0.1s; 4096 measurably broke the 1s
        # client-RTT bound under full-suite heap pressure) — the ramp
        # doubles per fast call, so a fast engine reaches whole chunks
        # within a handful of calls either way
        self.sync_merge_group = sync_merge_group
        self.sync_merge_budget = sync_merge_budget
        self.sync_initial_split = sync_initial_split
        self.tcp_backlog = tcp_backlog
        # process-parallel snapshot ingest (store/sharded_keyspace.py):
        # 0 = auto (CONSTDB_SHARDS / core count; 1 on <= 2 cores),
        # 1 = off.  Snapshots below the byte floor always take the plain
        # path — spawning shard workers costs more than they save there.
        self.ingest_shards = ingest_shards
        self.ingest_shard_min_bytes = ingest_shard_min_bytes
        # steady-state coalescing bounds for the pull path
        # (replica/coalesce.py); None = the CONSTDB_APPLY_BATCH /
        # CONSTDB_APPLY_LATENCY_MS env defaults.  apply_batch=1 pins a
        # node to the exact per-frame path.
        self.apply_batch = apply_batch
        self.apply_latency = apply_latency
        # batch wire protocol bounds for the push path (replica/link.py
        # + replica/wire.py): ops per REPLBATCH run and the aggregated
        # wire buffer's flush latency.  None = the CONSTDB_WIRE_BATCH /
        # CONSTDB_WIRE_LATENCY_MS env defaults; wire_batch=1 pins this
        # node to the byte-exact per-frame stream in BOTH directions
        # (it stops advertising CAP_BATCH_STREAM too — my_caps).
        from ..conf import env_float as _env_float, env_int as _env_int
        self.wire_batch = _env_int("CONSTDB_WIRE_BATCH", 512) \
            if wire_batch is None else wire_batch
        self.wire_latency = \
            (_env_float("CONSTDB_WIRE_LATENCY_MS", 5.0) / 1000.0) \
            if wire_latency is None else wire_latency
        # broadcast plane (round 17): negotiated stream/bulk compression
        # (CAP_COMPRESS — replica/link.py, utils/compressio.py) and the
        # encode-once run cache cap.  None = the CONSTDB_WIRE_COMPRESS /
        # CONSTDB_WIRE_COMPRESS_MIN / CONSTDB_ENCODE_CACHE_MB env
        # defaults; wire_compress=False is the kill switch for BOTH legs
        # (no outbound compression, no CAP_COMPRESS invitation), and
        # encode_cache_mb=0 makes every push loop re-encode (the
        # pre-broadcast path).  bulk_compress_level: zlib level for the
        # FULLSYNC/DELTASYNC container (latency-insensitive, so higher
        # than the per-section stream default).
        from ..conf import env_flag as _env_flag
        self.wire_compress = _env_flag("CONSTDB_WIRE_COMPRESS", True) \
            if wire_compress is None else wire_compress
        self.wire_compress_min = \
            _env_int("CONSTDB_WIRE_COMPRESS_MIN", 512) \
            if wire_compress_min is None else wire_compress_min
        self.bulk_compress_level = bulk_compress_level
        if encode_cache_mb is not None:
            node.wire_cache.configure(max(0, encode_cache_mb) << 20)
        # client-path coalescing (server/serve.py): max pipelined
        # commands planned into one columnar micro-merge.  None = the
        # CONSTDB_SERVE_BATCH env default; <= 1 pins every connection to
        # the exact per-command path (no coalescer is ever constructed).
        from ..conf import env_int
        self.serve_batch = env_int("CONSTDB_SERVE_BATCH", 512) \
            if serve_batch is None else serve_batch
        # shard-per-core serving (server/serve_shards.py): N worker
        # processes each owning a keyspace shard + engine + repl-log
        # segment, with this loop as the router/clock authority.  1 (the
        # default) never constructs the plane — the exact single-loop
        # path, byte for byte.
        self.serve_shards = env_int("CONSTDB_SERVE_SHARDS", 1) \
            if serve_shards is None else serve_shards
        # native intake stage (native/intake.cpp intake_scan): one C call
        # splits a coalescing connection's pipelined chunk AND classifies
        # the plannable commands into opcodes + pre-flattened payloads —
        # the per-command Python dispatch evaporates from the hot loop.
        # CONSTDB_NATIVE_INTAKE=0 pins the pure drain()+run_chunk path
        # (byte-identical; the stage is an accelerator, not a semantic).
        self.native_intake = env_int("CONSTDB_NATIVE_INTAKE", 1) > 0
        # digest-driven delta resync (replica/link.py _send_delta, wire
        # frames digest/digestack/deltasync): enabled by default — a
        # peer without CAP_DELTA_SYNC still gets the exact full-sync
        # byte stream.  delta_max_divergence = bucket-mismatch fraction
        # past which the pusher demotes to a full snapshot;
        # delta_bucket_keys = target keys per digest leaf bucket (finer
        # buckets localize random divergence at the cost of a larger
        # digest matrix — 8-byte hash per bucket, on the wire once per
        # refined shard).
        from ..conf import env_flag, env_float
        self.delta_sync = env_flag("CONSTDB_DELTA_SYNC", True) \
            if delta_sync is None else delta_sync
        self.delta_max_divergence = \
            env_float("CONSTDB_DELTA_MAX_DIVERGENCE", 0.5) \
            if delta_max_divergence is None else delta_max_divergence
        self.delta_bucket_keys = env_int("CONSTDB_DELTA_BUCKET_KEYS", 8) \
            if delta_bucket_keys is None else delta_bucket_keys
        # per-key stamp refinement floor: below this many keys in the
        # divergent buckets the level-2 exchange (~12B/listed key) can
        # cost more than the whole-bucket payload it would trim
        self.delta_stamp_min = env_int("CONSTDB_DELTA_STAMP_MIN", 4096) \
            if delta_stamp_min is None else delta_stamp_min
        # overload governance (server/overload.py + docs/INVARIANTS.md
        # "Degradation laws"): the node-level memory cap + watermarks
        # (None = the CONSTDB_MAXMEMORY / CONSTDB_MAXMEMORY_SOFT_PCT env
        # defaults — the governor read those at Node construction, so
        # only explicit overrides reconfigure it), the per-connection
        # reply-buffer cap past which a non-reading client is
        # disconnected, and the per-peer unacked replication window the
        # push loops pause on.
        if maxmemory is not None or maxmemory_soft_pct is not None:
            node.governor.configure(maxmemory, maxmemory_soft_pct)
        self.client_outbuf_max = \
            env_int("CONSTDB_CLIENT_OUTBUF_MAX", 128 << 20) \
            if client_outbuf_max is None else client_outbuf_max
        self.repl_window = env_int("CONSTDB_REPL_WINDOW", 16 << 20) \
            if repl_window is None else repl_window
        # durable op log (persist/oplog.py): every repl-log append
        # mirrors into crc-framed segment files, group-committed under
        # CONSTDB_AOF_FSYNC and compacted past CONSTDB_AOF_REWRITE_PCT.
        # None = the env defaults; start_node runs the boot recovery
        # (snapshot + oplog tail through the real merge path) and arms
        # node.oplog before the listener opens.
        from ..conf import env_flag as _aof_flag, env_str
        self.aof = _aof_flag("CONSTDB_AOF", False) if aof is None else aof
        self.aof_fsync = (env_str("CONSTDB_AOF_FSYNC", "everysec")
                          or "everysec") if aof_fsync is None else aof_fsync
        self.aof_rewrite_pct = env_int("CONSTDB_AOF_REWRITE_PCT", 100) \
            if aof_rewrite_pct is None else aof_rewrite_pct
        self.aof_rewrite_min_mb = \
            env_int("CONSTDB_AOF_REWRITE_MIN_MB", 16) \
            if aof_rewrite_min_mb is None else aof_rewrite_min_mb
        self.aof_dir = aof_dir or os.path.join(work_dir, "aof")
        # incremental checkpoints: a time-triggered rewrite cadence —
        # every checkpoint_secs (once the tail exceeds checkpoint_min_mb)
        # the log cuts a fresh generation behind a consistent snapshot,
        # keeping the restart tail short.  0 = size-triggered rewrites
        # only (the CONSTDB_AOF_REWRITE_PCT policy, unchanged).
        from ..conf import env_float
        self.checkpoint_secs = env_float("CONSTDB_CHECKPOINT_SECS", 0.0) \
            if checkpoint_secs is None else checkpoint_secs
        self.checkpoint_min_mb = \
            env_int("CONSTDB_CHECKPOINT_MIN_MB", 1) \
            if checkpoint_min_mb is None else checkpoint_min_mb
        # point-in-time restore: replay stops at this uuid and the log
        # re-bases on the next rewrite.  Run against a COPY of the dir.
        self.restore_to = restore_to
        # cluster mode (constdb_tpu/cluster): hash-slot keyspace
        # partitioning across replication groups.  None = the
        # CONSTDB_CLUSTER / CONSTDB_SLOT_GROUPS / CONSTDB_MIGRATE_
        # BATCH_MB env defaults; `cluster_group` is this node's group id
        # (harness/ops supplied — forked bench/chaos nodes pass it
        # directly).  Off (the default) node.cluster stays None and
        # every code path is the exact pre-cluster node.
        self.cluster = env_flag("CONSTDB_CLUSTER", False) \
            if cluster is None else cluster
        self.slot_groups = env_int("CONSTDB_SLOT_GROUPS", 1) \
            if slot_groups is None else slot_groups
        self.migrate_batch_mb = env_int("CONSTDB_MIGRATE_BATCH_MB", 8) \
            if migrate_batch_mb is None else migrate_batch_mb
        self.cluster_group = cluster_group
        if self.cluster and node.cluster is None:
            from ..cluster.slots import ClusterState, even_split
            node.cluster = ClusterState(
                cluster_group, even_split(max(1, self.slot_groups)))
            # slot ownership moving away invalidates every tracked key
            # hashing into the moved slots (server/tracking.py
            # slots_lost — the migration half of the tracking laws)
            node.cluster.on_slots_lost = node.tracking.slots_lost
        self.serve_plane = None
        # awaited by start() AFTER the serve plane is up but BEFORE the
        # listener opens — the sharded boot restore (start_node) runs
        # here so a reconnecting peer can never observe the un-fenced
        # merged repl_log (can_resume_from(cursor) on empty segments
        # would grant a PARTSYNC that silently omits every restored
        # key), and early clients never read half-restored shards
        self._boot_restore = None
        # peers silent beyond this stop pinning the GC horizon
        self.gc_peer_retention = gc_peer_retention
        node.replicas.gc_peer_retention_ms = int(gc_peer_retention * 1000)
        self._server: Optional[asyncio.base_events.Server] = None
        self._cron_task: Optional[asyncio.Task] = None
        self._conn_tasks: set[asyncio.Task] = set()
        # live client connections (server/tracking.py ClientConn), keyed
        # by the monotonically-minted client id — CLIENT ID/LIST and the
        # tracking registry's fan-out both read this
        self.client_conns: dict[int, object] = {}
        self._next_cid = 0
        self._closing = False
        from ..persist.share import SharedDump
        self.shared_dump = SharedDump(self)

    # ------------------------------------------------------------ lifecycle

    @property
    def advertised_addr(self) -> str:
        return self._advertised or f"{self.host}:{self.port}"

    def snapshot_ingest_shards(self, size: int) -> int:
        """How many hash shards a downloaded snapshot of `size` bytes
        should fan out over (1 = plain single-keyspace path)."""
        if size < self.ingest_shard_min_bytes:
            return 1
        n = self.ingest_shards
        if n == 0:
            from ..store.sharded_keyspace import default_shards
            n = default_shards()
        return max(1, n)

    async def start(self) -> None:
        os.makedirs(self.work_dir, exist_ok=True)
        if not self.node.node_id:
            # CRDT tie-breaks hinge on distinct writer node ids; an operator
            # who skips `node_id` in the config must not get three identical
            # writers (the reference defaults to 0 for everyone — conf.rs:63)
            import random as _random
            self.node.node_id = _random.SystemRandom().randrange(1, 1 << 31)
            log.info("auto-assigned node_id %d", self.node.node_id)
        self.node.stats.start_time = time.time()
        if self.serve_shards > 1:
            # spawn the shard workers BEFORE the listener opens (they
            # need the final node_id — workers stamp it into writes)
            from ..conf import env_str
            from .serve_shards import ServeShardPlane
            spec = env_str("CONSTDB_SHARD_ENGINE") or "cpu"
            self.serve_plane = ServeShardPlane(self, self.serve_shards,
                                               engine_spec=spec)
            await self.serve_plane.start()
        # bind (resolving an ephemeral port — advertised_addr is live
        # from here) but do NOT accept yet: the boot restore below must
        # land its watermark fences first
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port,
            backlog=self.tcp_backlog, start_serving=False)
        self.port = self._server.sockets[0].getsockname()[1]
        if self.node.cluster is not None:
            # our own group's address book entry: live as soon as the
            # (possibly ephemeral) port is known, so redirects and
            # gossiped tables name a dialable address
            self.node.cluster.table.groups.setdefault(
                self.node.cluster.my_gid, self.advertised_addr)
        if self._boot_restore is not None:
            await self._boot_restore()
        await self._server.start_serving()
        self._cron_task = asyncio.create_task(self._cron())
        # reconnect links for membership restored from a snapshot
        for m in self.node.replicas.live_peers():
            self.ensure_link(m)
        log.info("node %d listening on %s", self.node.node_id,
                 self.advertised_addr)

    async def close(self) -> None:
        self._closing = True
        if self._cron_task is not None:
            self._cron_task.cancel()
        for m in list(self.node.replicas.peers.values()):
            if isinstance(m.link, ReplicaLink):
                await m.link.stop()
        # stop accepting FIRST, then cancel handlers, then wait: on Python
        # 3.12+ Server.wait_closed waits for every spawned handler, so
        # waiting before the cancel sweep deadlocks on any live client —
        # and cancelling before close() would miss a handler accepted
        # during the link-stop awaits above
        if self._server is not None:
            self._server.close()
        await asyncio.sleep(0)  # let just-accepted handlers register
        for t in list(self._conn_tasks):
            t.cancel()
        if self._server is not None:
            await self._server.wait_closed()
        # second link sweep: a connection accepted just before the
        # listener closed can reach _upgrade_to_replica AFTER the sweep
        # above, registering a fresh link whose serve/push tasks would
        # outlive this app — a zombie stream that keeps a "closed" node
        # applying its peer's ops (found while pinning the ring-falloff
        # resync fallback: the zombie kept the restarted peer secretly
        # caught up, so the full-sync path never ran)
        for m in list(self.node.replicas.peers.values()):
            if isinstance(m.link, ReplicaLink):
                await m.link.stop()
        if self.serve_plane is not None:
            await self.serve_plane.close()
        if self.node.oplog is not None:
            # final group commit + close (policy `no` drains without
            # forcing an fsync — that is its contract)
            self.node.oplog.close()

    async def serve_forever(self) -> None:
        assert self._server is not None
        await self._server.serve_forever()

    # ----------------------------------------------------------------- cron

    async def _cron(self) -> None:
        """(reference server.rs:134-146: 100ms tick — advance uuid, gc).

        The tick sleep doubles as an event wait: a key-level delete
        (EVENT_DELETED — new garbage) or an advanced ack watermark
        (EVENT_REPLICA_ACKED — the horizon moved) triggers a GC sweep at
        the next tick instead of waiting out the full gc_interval."""
        from .events import EVENT_DELETED, EVENT_REPLICA_ACKED
        consumer = self.node.events.new_consumer(
            EVENT_DELETED | EVENT_REPLICA_ACKED)
        last_gc = 0.0
        loop = asyncio.get_running_loop()
        x = self.node.stats.extra
        try:
            while True:
                t0 = loop.time()
                woke = await consumer.wait(timeout=0.1)
                self.node.hlc.tick(False)
                now = loop.time()
                if not woke:
                    # event-loop lag: how far past the tick timeout this
                    # wake actually ran — the operator's view of intake
                    # saturation (a wedged loop shows up HERE first)
                    lag_ms = max(0.0, (now - t0 - 0.1) * 1000.0)
                    x["loop_lag_ms"] = round(lag_ms, 2)
                    if lag_ms > x.get("loop_lag_ms_max", 0.0):
                        x["loop_lag_ms_max"] = round(lag_ms, 2)
                # watermark re-check each tick: replication intake and
                # pool growth move used_memory without any client write
                # ever consulting the gate (server/overload.py)
                self.node.governor.tick()
                oplog = self.node.oplog
                if oplog is not None:
                    # everysec group commits, watermark records, and the
                    # rewrite-compaction check (persist/oplog.py)
                    await oplog.cron(self)
                due = now - last_gc >= self.gc_interval
                early = woke and now - last_gc >= self.gc_interval / 4
                if due or early:
                    if self.serve_plane is not None:
                        await self.serve_plane.gc(self.node.gc_horizon())
                    else:
                        self.node.gc()
                    last_gc = now
        finally:
            consumer.close()

    # ---------------------------------------------------------------- links

    async def open_peer_connection(self, host: str, port: int):
        """Dial a replica peer (replica/link.py _dial_once).  Routed
        through `peer_connector` when one is installed (the chaos
        harness's fault plane); a plain TCP connection otherwise."""
        if self.peer_connector is not None:
            return await self.peer_connector(host, port)
        return await asyncio.open_connection(host, port)

    def ensure_link(self, meta: ReplicaMeta) -> None:
        """Spawn (or keep) the dialing link for a live peer."""
        if not meta.alive or meta.addr == self.advertised_addr:
            return
        if isinstance(meta.link, ReplicaLink):
            meta.link.start()
            return
        ReplicaLink(self, meta).start()

    async def drop_link(self, meta: ReplicaMeta) -> None:
        if isinstance(meta.link, ReplicaLink):
            await meta.link.stop()

    # ----------------------------------------------------------- connection

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        if self._closing:  # raced the listener shutdown: refuse outright
            writer.close()
            return
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        self.node.stats.connections_accepted += 1
        self.node.stats.current_clients += 1
        from .tracking import ClientConn
        self._next_cid += 1
        try:
            peer = writer.get_extra_info("peername")
            addr = f"{peer[0]}:{peer[1]}" if peer else "?"
        except (AttributeError, OSError, IndexError):  # pragma: no cover
            addr = "?"
        client = ClientConn(self._next_cid, addr, writer,
                            created=time.time())
        self.client_conns[client.cid] = client
        try:
            # bound the transport's userspace reply buffer: drain()
            # engages at the high-water mark, so one connection's
            # in-flight pipeline depth is one chunk of replies — a
            # stalled reader parks its coroutine at the mark instead of
            # growing the buffer (the outbuf cap below catches the case
            # where a single chunk's replies blow straight past it)
            writer.transport.set_write_buffer_limits(
                high=min(self.client_outbuf_max or (1 << 18), 1 << 18))
        except (AttributeError, RuntimeError):  # pragma: no cover
            pass
        parser = make_parser()
        out = bytearray()
        upgraded = False
        plane = self.serve_plane
        coal = None
        if plane is None and self.serve_batch > 1:
            # pipelined chunks are PLANNED instead of executed
            # per message (server/serve.py); serve_batch <= 1
            # (CONSTDB_SERVE_BATCH=1) keeps the exact per-command loop.
            # With a serve PLANE active the chunk is ROUTED instead
            # (server/serve_shards.py) — the workers own the coalescers.
            from .serve import ServeCoalescer
            coal = ServeCoalescer(self.node, max_run=self.serve_batch,
                                  client=client)
        try:
            while True:
                data = await reader.read(_READ_CHUNK)
                if not data:
                    break
                self.node.stats.net_in_bytes += len(data)
                parser.feed(data)
                if coal is None and plane is None:
                    while (msg := parser.next_msg()) is not None:
                        if self._is_sync(msg):
                            # replies for commands pipelined BEFORE the
                            # SYNC must reach the client before the
                            # handshake reply takes over the stream
                            await self._aof_ack_barrier()
                            out = self._flush_out(writer, out)
                            self._upgrade_to_replica(msg, reader, writer,
                                                     parser)
                            upgraded = True
                            break
                        reply = self.node.execute(msg, client=client)
                        if not isinstance(reply, NoReply):
                            encode_into(out, reply)
                else:
                    if coal is not None and self.native_intake:
                        # native intake stage: the C scanner owns every
                        # leading well-formed flat frame (split +
                        # classify in one call); whatever it stops at —
                        # partial frame, SYNC upgrade, malformed bytes,
                        # nested array — stays buffered for the pure
                        # drain() below, which keeps the reference
                        # behavior for those frames byte for byte
                        while (nat := parser.native_drain()) is not None:
                            stats = self.node.stats
                            stats.native_intake_chunks += 1
                            stats.native_intake_msgs += len(nat[0])
                            coal.run_native_chunk(nat[0], nat[1], out)
                    msgs = parser.drain()
                    for i, msg in enumerate(msgs):
                        if self._is_sync(msg):
                            # messages after the SYNC belong to the
                            # replica link's stream — hand them back
                            # before the link adopts the parser
                            parser.pushback(msgs[i + 1:])
                            if i:
                                await self._run_chunk(plane, coal,
                                                      msgs[:i], out, client)
                            await self._aof_ack_barrier()
                            out = self._flush_out(writer, out)
                            self._upgrade_to_replica(msg, reader, writer,
                                                     parser)
                            upgraded = True
                            break
                    else:
                        if msgs:
                            await self._run_chunk(plane, coal, msgs, out,
                                                  client)
                if upgraded:
                    return  # connection now owned by the replica link
                if out:
                    # fsync=always ack gate: replies reach the socket
                    # only after the group commit covering this chunk's
                    # appends lands — one fsync per pipelined chunk,
                    # riding the coalescer's end-of-chunk flush barrier
                    await self._aof_ack_barrier()
                    out = self._flush_out(writer, out)
                    if self._outbuf_overflow(writer):
                        return  # disconnected loudly; finally cleans up
                    await writer.drain()
        except (ConnectionError, OSError, asyncio.IncompleteReadError):
            pass
        except CstError as e:
            # a malformed frame mid-pipeline: replies already encoded in
            # `out` for earlier completed commands must still reach the
            # client (dropping them desyncs its pipeline accounting), and
            # messages that parsed cleanly before the bad frame still
            # execute (the parser stashed them — take_queued)
            try:
                salvaged = parser.take_queued()
                sync_at = next((i for i, m in enumerate(salvaged)
                                if self._is_sync(m)), -1)
                if sync_at >= 0:
                    # a SYNC parsed clean before the bad frame: execute
                    # the prefix, hand the rest back, and upgrade — the
                    # malformed bytes stay in the parser and surface on
                    # the link's stream (the per-command loop's behavior)
                    head, syn = salvaged[:sync_at], salvaged[sync_at]
                    parser.pushback(salvaged[sync_at + 1:])
                    salvaged = head
                if salvaged:
                    if coal is not None or plane is not None:
                        await self._run_chunk(plane, coal, salvaged, out,
                                              client)
                    else:
                        for msg in salvaged:
                            reply = self.node.execute(msg, client=client)
                            if not isinstance(reply, NoReply):
                                encode_into(out, reply)
                await self._aof_ack_barrier()
                if sync_at >= 0:
                    out = self._flush_out(writer, out)
                    self._upgrade_to_replica(syn, reader, writer, parser)
                    upgraded = True
                    return
                encode_into(out, Err(e.resp_error()))
                out = self._flush_out(writer, out)
                await writer.drain()
            except (ConnectionError, OSError):
                pass
        finally:
            self.node.stats.current_clients -= 1
            self._conn_tasks.discard(task)
            # tracking state dies with the connection (the liveness half
            # of the invalidate-before-visible law): a client's cached
            # entries are only trustworthy while the connection that
            # filled them lives, so the server forgets the subscription
            # the moment it can no longer deliver pushes on it
            if client.tracking:
                self.node.tracking.unsubscribe(client)
            client.writer = None
            self.client_conns.pop(client.cid, None)
            # an upgraded connection is owned by its replica link now
            if not upgraded and not writer.is_closing():
                writer.close()

    async def _aof_ack_barrier(self) -> None:
        """fsync=always group commit before replies flush (no-op for
        every other policy, and when nothing is pending)."""
        oplog = self.node.oplog
        if oplog is not None and oplog.ack_barrier_needed:
            await oplog.ack_barrier()

    async def _run_chunk(self, plane, coal, msgs: list,
                         out: bytearray, client=None) -> None:
        """One drained pipelined chunk, through whichever machinery this
        node runs: the shard-routing plane (serve_shards > 1) or the
        in-loop coalescer (serve_batch > 1).  `client` is the
        connection's ClientConn (HELLO / CLIENT TRACKING state) — the
        coalescer already carries it; the shared plane takes it per
        chunk."""
        if plane is not None:
            await plane.run_chunk(msgs, out, client=client)
        else:
            coal.run_chunk(msgs, out)

    def _outbuf_overflow(self, writer) -> bool:
        """Slow-client protection (CONSTDB_CLIENT_OUTBUF_MAX): a client
        whose un-drained reply bytes pass the cap is disconnected LOUDLY
        — counted, logged, transport aborted (it is not reading; a
        graceful close would park on the very buffer being dropped) —
        instead of pinning unbounded reply memory on the loop.  The
        disconnect is connection-fatal but never state-corrupting: every
        landed write already landed; only undelivered reply bytes drop
        (docs/INVARIANTS.md "Degradation laws")."""
        cap = self.client_outbuf_max
        if not cap:
            return False
        tr = writer.transport
        if tr is None or tr.get_write_buffer_size() <= cap:
            return False
        self.node.stats.client_outbuf_disconnects += 1
        try:
            peer = writer.get_extra_info("peername")
        except (AttributeError, OSError):  # pragma: no cover
            peer = None
        log.warning(
            "client %s disconnected: reply buffer %d bytes over "
            "CONSTDB_CLIENT_OUTBUF_MAX=%d (reader stalled)", peer,
            tr.get_write_buffer_size(), cap)
        tr.abort()
        return True

    def _flush_out(self, writer, out: bytearray) -> bytearray:
        """Queue accumulated replies on the transport and return a fresh
        buffer.  Buffer SWAP instead of bytes(out): ownership moves to
        the transport (which copies only what it cannot send
        immediately) — no reply-buffer copy per chunk.  Also used before
        a SYNC upgrade takes the stream over, so pipelined-before-SYNC
        replies are not dropped."""
        if out:
            self.node.stats.net_out_bytes += len(out)
            writer.write(out)
            out = bytearray()
        return out

    @staticmethod
    def _is_sync(msg) -> bool:
        return (isinstance(msg, Arr) and msg.items
                and isinstance(msg.items[0], Bulk)
                and msg.items[0].val.lower() == SYNC)

    def _upgrade_to_replica(self, msg, reader, writer, parser) -> None:
        """Passive handshake: register/refresh the peer, reply `sync 1`,
        hand the connection to its link."""
        if self._closing:  # the second close() sweep would stop the link,
            writer.close()  # but never adopting is cheaper and race-free
            return
        items = msg.items
        try:
            role = as_int(items[1])
            peer_id = as_int(items[2])
            peer_alias = as_bytes(items[3]).decode("utf-8", "replace")
            peer_addr = as_bytes(items[4]).decode("utf-8", "replace")
            peer_resume = as_int(items[5])
            # capability bits (replica/link.py CAP_*); a pre-capability
            # peer sends 6-item frames — tolerate, never assume support
            peer_caps = as_int(items[6]) if len(items) > 6 else 0
        except (IndexError, CstError):
            writer.write(b"-malformed sync\r\n")
            writer.close()
            return
        if role != 0 or peer_addr == self.advertised_addr:
            writer.write(b"-bad sync role or self-sync\r\n")
            writer.close()
            return
        node = self.node
        prev = node.replicas.get(peer_addr)
        if prev is not None and not prev.alive:
            # FORGET must stick: a tombstoned peer's SYNC is rejected until
            # an explicit MEET re-admits the address (Redis CLUSTER
            # FORGET-style ban).  Auto-re-adding here resurrected forgotten
            # peers across the whole mesh within one reconnect_delay.
            # structured error CODE (first token) — the dialing link matches
            # on this prefix to suspend, so an unrelated error that merely
            # mentions the word can never trip it (replica/link.py)
            writer.write(b"-FORGOTTEN removed from this mesh; "
                         b"an explicit MEET is required to rejoin\r\n")
            writer.close()
            return
        newly_met = prev is None
        meta = node.replicas.add(peer_addr, node.hlc.tick(True),
                                 node_id=peer_id, alias=peer_alias)
        if newly_met:
            # replicate the introduction so the whole mesh learns this peer
            # even when every sync is partial and no snapshot (with its
            # REPLICAS section) ever flows — the reference only propagates
            # membership through full syncs (pull.rs:136-153), which leaves
            # hub-and-spoke topologies permanently partitioned
            node.execute([Bulk(b"meet"), Bulk(peer_addr.encode())])
        from ..replica.link import my_caps
        writer.write(encode_msg_arr([
            Bulk(SYNC), Int(1), Int(node.node_id), Bulk(node.alias.encode()),
            Bulk(self.advertised_addr.encode()), Int(meta.uuid_he_sent),
            Int(my_caps(self, meta))]))
        link = meta.link if isinstance(meta.link, ReplicaLink) else \
            ReplicaLink(self, meta)
        link.adopt(reader, writer, parser, peer_resume, peer_caps=peer_caps)
        link.start()  # dial loop doubles as the reconnect supervisor


def encode_msg_arr(items) -> bytes:
    out = bytearray()
    encode_into(out, Arr(items))
    return bytes(out)


def _quarantine_snapshot(node: Node, path: str, err: BaseException) -> str:
    """Boot-resilience for a truncated/bit-flipped snapshot: rename it
    aside (`.corrupt` — evidence for the operator, and the crash-loop
    breaker: the next boot no longer sees it), log LOUDLY, and flag it
    in INFO (`boot_snapshot_quarantined`).  The node then boots EMPTY
    and rejoins the mesh as a fresh replica — degraded but alive, which
    beats a node that can never start."""
    qpath = path + ".corrupt"
    try:
        os.replace(path, qpath)
    except OSError as mv_err:  # pragma: no cover - fs-dependent
        log.error("could not quarantine corrupt snapshot %s: %s",
                  path, mv_err)
        qpath = path
    log.error("boot snapshot %s is unreadable (%s: %s); quarantined to "
              "%s — booting EMPTY", path, type(err).__name__, err, qpath)
    node.stats.extra["boot_snapshot_quarantined"] = qpath
    return qpath


# what a damaged snapshot file can surface as through the loader: framing
# and checksum failures (InvalidSnapshot*), section-decode failures the
# loader does not wrap (ValueError/KeyError/OverflowError from a
# bit-flipped length or enum), and plain IO errors
_SNAPSHOT_LOAD_ERRORS = (CstError, OSError, ValueError, KeyError,
                         IndexError, OverflowError, EOFError)


def _schedule_cache_warm(app: ServerApp) -> None:
    """Digest crc caches warm OFF the boot path: an executor thread
    fills them after the listener opens (keyspace.warm_digest_caches
    takes its own lock — the replica-link digest path uses the same
    off-loop discipline), so restart wall time measures replay, not
    cache rebuilds.  The read cache stays cold until traffic arrives."""
    node = app.node
    loop = asyncio.get_event_loop()
    t0 = time.monotonic()

    def _warm() -> None:
        try:
            node.ks.warm_digest_caches()
            node.stats.extra["digest_warm_s"] = round(
                time.monotonic() - t0, 3)
        except Exception:  # noqa: BLE001 - warming is best-effort
            log.exception("digest cache warm failed")

    loop.run_in_executor(None, _warm)


async def start_node(node: Node, **kwargs) -> ServerApp:
    """Convenience: build + start a ServerApp (optionally restoring the
    boot snapshot — a capability the reference lacks, SURVEY.md §5.4)."""
    app = ServerApp(node, **kwargs)
    if app.aof:
        # durable op log: boot recovery = chosen snapshot (the AOF base
        # when one exists, the boot snapshot otherwise) + the oplog
        # tail replayed through the REAL merge path, with torn-tail
        # repair and the watermark consistency-cut rules
        # (persist/oplog.py).  A corrupt snapshot quarantines and falls
        # back to AOF-only replay — the log is quarantined too only
        # when it is itself unreadable.
        from ..persist import oplog as oplog_mod
        if app.serve_shards > 1:
            if not node.node_id:
                nid = oplog_mod.prescan_node_id(app.aof_dir,
                                                app.snapshot_path)
                if nid:
                    node.node_id = nid

            async def _restore_aof_plane() -> None:
                t0 = time.monotonic()
                await oplog_mod.recover_into_plane(
                    app, restore_to=app.restore_to)
                node.stats.extra["recovery_wall_s"] = round(
                    time.monotonic() - t0, 3)
                if app.restore_to and node.oplog is not None:
                    # cut the fresh base NOW (arm flagged the log
                    # dirty): the tail above the restore target must
                    # never replay again
                    await node.oplog.rewrite(app)

            app._boot_restore = _restore_aof_plane
            await app.start()
            _schedule_cache_warm(app)
            return app
        t0 = time.monotonic()
        info = oplog_mod.recover(node, app.aof_dir,
                                 boot_snapshot=app.snapshot_path,
                                 engine=node.engine,
                                 restore_to=app.restore_to)
        lg = oplog_mod.arm(app, info)
        node.stats.extra["recovery_wall_s"] = round(
            time.monotonic() - t0, 3)
        await app.start()
        if app.restore_to:
            # see the sharded branch above: re-base immediately
            await lg.rewrite(app)
        _schedule_cache_warm(app)
        return app
    if app.serve_shards > 1:
        # shard-per-core node: workers ARE the store, so the boot
        # snapshot fans out to them — which requires the plane up first
        # (start()).  The snapshot's node identity is pre-scanned so the
        # workers spawn with the RESTORED node_id; the data ingest +
        # watermark fences run as start()'s boot-restore hook, after the
        # plane is up but BEFORE the listener opens — the same
        # fence-before-serving order the plain path below enforces, for
        # the same reason (see its comment: an un-fenced log grants
        # divergent PARTSYNCs).
        from ..persist.snapshot import SectionDemux, SnapshotLoader
        loop = asyncio.get_event_loop()
        restore = app.snapshot_path and os.path.exists(app.snapshot_path)
        if restore and not node.node_id:
            try:
                f = await loop.run_in_executor(None, open,
                                               app.snapshot_path, "rb")
                try:
                    for kind, payload in SnapshotLoader(f):
                        if kind == "node":
                            if payload.node_id:
                                node.node_id = payload.node_id
                            break
                finally:
                    f.close()
            except _SNAPSHOT_LOAD_ERRORS as e:
                _quarantine_snapshot(node, app.snapshot_path, e)
                restore = False
        if restore:

            async def restore_into_plane() -> None:
                f = await loop.run_in_executor(None, open,
                                               app.snapshot_path, "rb")
                demux = SectionDemux(f)
                try:
                    await app.serve_plane.ingest_batches(demux.batches())
                except _SNAPSHOT_LOAD_ERRORS as e:
                    # a mid-file corruption can strand a PARTIAL restore
                    # in the workers: wipe them so "boots empty" is
                    # really empty, then quarantine + serve
                    await app.serve_plane.pool.call_all("reset")
                    _quarantine_snapshot(node, app.snapshot_path, e)
                    return
                finally:
                    f.close()
                if demux.meta is not None:
                    node.hlc.observe(demux.meta.repl_last_uuid)
                    node.repl_log.last_uuid = demux.meta.repl_last_uuid
                    node.repl_log.evicted_up_to = demux.meta.repl_last_uuid
                    node.replicas.merge_records(
                        demux.replica_rows, my_addr=app.advertised_addr,
                        adopt_watermarks=True)
                    log.info("restored snapshot %s into %d serve shards",
                             app.snapshot_path, app.serve_shards)

            app._boot_restore = restore_into_plane
        await app.start()
        return app
    if app.snapshot_path and os.path.exists(app.snapshot_path):
        from ..persist.snapshot import load_snapshot
        try:
            meta, records = load_snapshot(app.snapshot_path, node.ks,
                                          engine=node.engine)
        except _SNAPSHOT_LOAD_ERRORS as e:
            # a truncated/bit-flipped file can fail MID-merge: discard
            # whatever partial state landed (fresh keyspace + resident
            # mirrors) so the quarantined boot is really empty, not a
            # silent partial restore a peer would then merge against
            if hasattr(node.engine, "discard_resident"):
                node.engine.discard_resident()
            node.ks = node._make_keyspace()
            _quarantine_snapshot(node, app.snapshot_path, e)
        else:
            if meta.node_id and not node.node_id:
                node.node_id = meta.node_id
            node.hlc.observe(meta.repl_last_uuid)
            # The fresh repl_log does not cover any of the restored
            # history, so a peer resuming below the restored watermark
            # MUST get a full snapshot — with last_uuid/evicted_up_to
            # left at 0, can_resume_from(0) would be true and the push
            # loop would serve PARTSYNC that silently omits every
            # restored key (permanent divergence).  Same rule the
            # reference applies when the resume point falls outside the
            # ring (push.rs:95-110).
            node.repl_log.last_uuid = meta.repl_last_uuid
            node.repl_log.evicted_up_to = meta.repl_last_uuid
            # snapshot-backed: the restored keyspace carries the state
            # behind the recorded watermarks, so adopting them is
            # lossless (and required — see merge_records)
            node.replicas.merge_records(records,
                                        my_addr=app.advertised_addr,
                                        adopt_watermarks=True)
            log.info("restored snapshot %s (%d keys)", app.snapshot_path,
                     node.ks.n_keys())
    await app.start()
    return app
