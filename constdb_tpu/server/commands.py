"""Command dispatch table and handlers — the node's client API surface.

Capability parity with the reference's command layer (reference src/cmd.rs
static COMMANDS table + Cmd::exec, src/type_counter.rs, src/type_set.rs,
src/type_hash.rs), over the columnar KeySpace instead of per-key heap
objects.

Dispatch contract (reference src/cmd.rs:43-63):
  * client commands mint a fresh HLC uuid; replicated commands run with the
    ORIGINATOR's (nodeid, uuid) and are never re-replicated.
  * on success, WRITE commands without NO_REPLICATE are appended verbatim to
    the repl_log; NO_REPLICATE handlers may push rewritten commands
    themselves (DEL rewrites into delcnt/delbytes/delset/deldict —
    reference src/cmd.rs:220-296).
  * REPL_ONLY commands are rejected from clients; CLIENT_ONLY commands are
    rejected from the replication stream (an enforcement the reference
    documents but does not code — src/cmd.rs:220 comment).

Deliberate fixes over the reference (documented in crdt/semantics.py):
  * SPOP replicates the deterministic rewrite `srem key <member>` instead of
    replaying the random pop on every replica (reference type_set.rs:85-117
    would diverge).
  * uuid minting is write-only (the reference's `flags | COMMAND_WRITE > 0`
    precedence bug makes every command a write — src/cmd.rs:49).
  * applying a replicated command advances the local HLC past the origin
    uuid, so later local writes sort after everything already seen.
  * EXPIRE/EXPIREAT/TTL exist (the reference ships the expiry machinery with
    no command — SURVEY.md §"Known reference defects"); expiry merges as
    max, so EXPIRE extends but never shortens a TTL.
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Optional, TYPE_CHECKING

from ..crdt import semantics as S
from ..errors import (CstError, InvalidRequestMsg, UnknownCmd, UnknownSubCmd,
                      WrongArity)
from ..resp.message import (Arr, Bulk, Err, Int, Msg, NIL, NO_REPLY, OK,
                            as_bytes, as_int, as_uint)
from ..store.keyspace import FAMILIES as ALL_FAMILIES
from ..utils.hlc import now_ms, SEQ_BITS

if TYPE_CHECKING:
    from .node import Node

# --- command flags (parity: reference src/cmd.rs:80-85) ---
CMD_READONLY = 1
CMD_WRITE = 2
CMD_CTRL = 4
CMD_NO_REPLICATE = 8
CMD_NO_REPLY = 16
CMD_REPL_ONLY = 32
CMD_CLIENT_ONLY = 64
# data-GROWING client writes: shed with a clean -OOM error past the
# maxmemory soft watermark (server/overload.py).  Deletes/removals/
# expiry are deliberately NOT flagged (they free memory), admin and
# membership never are, and the replication path never consults the
# flag at all — replicated ops must always land or the mesh diverges
# (docs/INVARIANTS.md "Degradation laws").
CMD_DENYOOM = 128


class Command:
    __slots__ = ("name", "handler", "flags", "families")

    def __init__(self, name: bytes, handler: Callable, flags: int,
                 families: tuple = ALL_FAMILIES):
        self.name = name
        self.handler = handler
        self.flags = flags
        # CRDT planes a write can touch — scopes the keyspace version bump
        # so a resident merge engine only drops the device mirrors this
        # command could actually have invalidated (engine/tpu.py)
        self.families = families

    @property
    def is_write(self) -> bool:
        return bool(self.flags & CMD_WRITE)


COMMANDS: dict[bytes, Command] = {}


def register(name: str, flags: int, families: tuple = ALL_FAMILIES):
    def deco(fn):
        cmd = Command(name.encode(), fn, flags, families)
        COMMANDS[cmd.name] = cmd
        return fn
    return deco


# --------------------------------------------------------------------
# read plane classification (server/serve.py read planner + the
# dispatch-time narrow flush below).
# --------------------------------------------------------------------

class ReadSpec:
    """How the serve coalescer's read planner executes one key-scoped
    read command as part of a batched read run (server/serve.py
    _run_read_batch): `kind` selects the vectorized gather + reply
    shape, `enc` the required key encoding (None = get's own dispatch),
    `families` the CRDT planes the read observes (its narrow
    flush-before-read set), `arity` the exact frame length the planner
    accepts (anything else falls back to the per-command path, which
    raises the exact arity/type error)."""

    __slots__ = ("kind", "enc", "families", "arity")

    def __init__(self, kind: str, enc, families: tuple, arity: int):
        self.kind = kind
        self.enc = enc
        self.families = families
        self.arity = arity


SERVE_READS: dict[bytes, ReadSpec] = {}

# Which CRDT planes each READONLY command observes — the dispatch-time
# narrow read barrier: execute() flushes ONLY these families for a
# listed read (ensure_flushed_for), so a device-resident engine whose
# listed planes are clean serves the read with ZERO flush downloads
# (the TENSOR.GET device-first pattern from round 13, generalized to
# the scalar families).  Reads not listed here (desc, INFO-adjacent
# probes) keep the blanket flush.  The tensor reads observe only the
# env plane on host — their payload truth stays in the resident device
# pools (Node.tensor_read); see the note at the old TENSOR_DEVICE_READS
# site in the dispatch body.
READ_FLUSH_FAMILIES: dict[bytes, tuple] = {
    b"get": ("env", "reg", "cnt"),
    b"smembers": ("env", "el"),
    b"scnt": ("env", "el"),
    b"sismember": ("env", "el"),
    b"hget": ("env", "el"),
    b"hgetall": ("env", "el"),
    b"hlen": ("env", "el"),
    b"lrange": ("env", "el"),
    b"llen": ("env", "el"),
    b"mvget": ("env", "el"),
    b"ttl": ("env",),
    b"tensor.get": ("env",),
    b"tensor.stat": ("env",),
}


def serve_read(name: str, kind: str, enc=None, arity: int = 2):
    """Register the command `name` with the serve-path READ planner
    (stacked ABOVE @register so the command exists when this runs).
    Planned reads are served from batched gathers + the versioned reply
    cache instead of acting as per-command barriers; the family set the
    plan flushes comes from READ_FLUSH_FAMILIES (one source for the
    lone-read and batched-read narrow barriers), and the KEY-CONFINED
    lint rule statically checks the decorated handler like it does the
    write planners' (constdb_tpu/analysis/rules.py) — the read planner
    routes and caches by the FIRST argument alone."""
    def deco(fn):
        cmd = COMMANDS[name.encode()]
        assert cmd.flags & CMD_READONLY, name
        SERVE_READS[cmd.name] = ReadSpec(
            kind, enc, READ_FLUSH_FAMILIES[cmd.name], arity)
        return fn
    return deco


class ArgIter:
    """Arity-checked argument cursor (parity: reference NextArg,
    src/cmd.rs:348-397)."""

    __slots__ = ("items", "pos", "cmd")

    def __init__(self, items: list, cmd: str = ""):
        self.items = items
        self.pos = 0
        self.cmd = cmd

    def _next(self) -> Msg:
        if self.pos >= len(self.items):
            cmd = self.cmd
            if isinstance(cmd, bytes):
                cmd = cmd.decode("utf-8", "replace")
            raise WrongArity(cmd)
        m = self.items[self.pos]
        self.pos += 1
        return m

    def next_bytes(self) -> bytes:
        return as_bytes(self._next())

    def next_int(self) -> int:
        return as_int(self._next())

    def next_uint(self) -> int:
        return as_uint(self._next())

    def next_str(self) -> str:
        return self.next_bytes().decode("utf-8", "replace")

    @property
    def has_more(self) -> bool:
        return self.pos < len(self.items)

    def rest_bytes(self) -> list[bytes]:
        out = []
        while self.has_more:
            out.append(self.next_bytes())
        return out


class ExecCtx:
    """Per-execution context: who wrote, at what HLC time, via which path."""

    __slots__ = ("uuid", "nodeid", "from_repl", "client")

    def __init__(self, uuid: int, nodeid: int, from_repl: bool, client=None):
        self.uuid = uuid
        self.nodeid = nodeid
        self.from_repl = from_repl
        self.client = client


def execute(node: "Node", req, client=None, uuid=None) -> Msg:
    """Client-path dispatch (reference Cmd::exec, src/cmd.rs:43-53).

    `uuid`: a pre-minted HLC uuid for this command (shard-per-core
    serving, server/serve_shards.py — the PARENT process is the clock
    authority and mints at route time with the same tick(is_write)
    discipline this function applies, so the uuid a worker receives is
    exactly the one a single-loop node would have minted here).  None =
    mint locally (the default, and the only path on shards=1)."""
    items = req.items if isinstance(req, Arr) else list(req)
    if not items:
        return Err(b"empty command")
    head = items[0]
    name = head.val if type(head) is Bulk else None
    if name is None:
        try:
            name = as_bytes(head)
        except CstError as e:
            return Err(e.resp_error())
    cmd = COMMANDS.get(name)
    if cmd is None:
        # commands usually arrive lowercase already; pay for .lower() only
        # on the miss
        name = name.lower()
        cmd = COMMANDS.get(name)
        if cmd is None:
            return Err(UnknownCmd(name.decode("utf-8", "replace")).resp_error())
    if cmd.flags & CMD_REPL_ONLY:
        return Err(b"this command can only be sent by replicas")
    node.stats.cmds_processed += 1
    cl = node.cluster
    if cl is not None and len(items) > 1 and shard_routable(cmd):
        # slot routing (cluster/slots.py): every data command is FIRST-
        # KEY-CONFINED (the KEY-CONFINED lint convention), so the slot
        # decision needs only items[1].  A redirect mints NO uuid,
        # touches NO state, and replicates NOTHING — to this node the
        # command never happened.  The replication path never routes:
        # replicated ops are already group-scoped by construction (the
        # writer routed), and must always land (apply_replicated).
        try:
            redirect = cl.route(as_bytes(items[1]), cmd.is_write)
        except CstError:
            redirect = None  # unkeyable arg: the handler's exact error
        if redirect is not None:
            return redirect
    if cmd.flags & CMD_DENYOOM and node.governor.shed_writes():
        # maxmemory shed, at the CLIENT edge only: nothing was applied,
        # logged, or replicated — this write never existed, so the
        # mesh's delivered set (and its convergence) is untouched.  The
        # replication path (apply_replicated) never gates: replicated
        # ops must always land (server/overload.py module doc).
        node.stats.oom_shed_writes += 1
        from .overload import OOM_ERR
        return Err(OOM_ERR)
    fams = READ_FLUSH_FAMILIES.get(name)
    if fams is not None:
        # narrow read barrier: a listed read observes only `fams`, so a
        # resident engine flushes nothing when those planes are clean.
        # The tensor reads additionally serve DEVICE-FIRST
        # (Node.tensor_read): they touch only the env plane on host and
        # the host-authoritative slot stamps — the payload truth stays
        # in the resident pools, so the blanket flush would force the
        # very dirty-row round-trip the steady tensor path exists to
        # avoid.
        node.ensure_flushed_for(fams)
    else:
        node.ensure_flushed()  # device merge results become readable
    if uuid is None:
        uuid = node.hlc.tick(cmd.is_write)
    ctx = ExecCtx(uuid, node.node_id, False, client)
    args = ArgIter(items[1:], name)
    try:
        reply = cmd.handler(node, ctx, args)
    except CstError as e:
        if cmd.is_write:
            _invalidate_read_cache(node, cmd, items[1:])
        return Err(e.resp_error())
    if cmd.is_write:
        node.ks.touch(*cmd.families)
        # invalidate-before-visible: the reply cache drops this key's
        # entries before any later read can observe the write
        # (server/read_cache.py; every data command is first-key-
        # confined, the KEY-CONFINED convention — element writes
        # member-scoped on this success path)
        _invalidate_read_cache(node, cmd, items[1:], scoped=True)
        if not (cmd.flags & CMD_NO_REPLICATE):
            node.replicate_cmd(uuid, name, items[1:])
    elif client is not None and client.tracking == 1 and \
            fams is not None and len(items) > 1:
        # default-mode client tracking (server/tracking.py): record the
        # key this tracked connection just read — the listed key-scoped
        # reads (READ_FLUSH_FAMILIES) are exactly the first-key-confined
        # data reads, so items[1] is the one key the reply observes
        try:
            node.tracking.note_read(client, as_bytes(items[1]))
        except CstError:
            pass
    return reply


# element writes whose touched members are exactly their args —
# member-scoped reply-cache invalidation (sismember/hget entries for
# OTHER members survive; read_cache.invalidate_key_members).  The value
# is the arg stride (hset interleaves field/value pairs).
_MEMBER_WRITE_STRIDE = {b"sadd": 1, b"srem": 1, b"hdel": 1, b"hset": 2}


def _invalidate_read_cache(node: "Node", cmd: Command, args: list,
                           scoped: bool = False) -> None:
    """Reply-cache intake hook for the per-command write paths (client
    dispatch + per-frame replication apply).  Membership commands
    (empty `families`) touch no keyspace state; CTRL takes subcommands,
    not keys, so it clears outright rather than mis-scope; everything
    else is first-key-confined — and element writes additionally
    member-scoped when `scoped` (the SUCCESS path only: an errored
    handler gets the conservative whole-key drop).  Invalidating on the
    ERROR path too is deliberate — a handler that raised mid-mutation
    must not leave a stale cached reply behind.

    The tracked-client push stream (server/tracking.py) taps the same
    seam under its own gate: tracking is key-granular on the wire, so
    member-scoped writes still push the whole key."""
    tr = node.tracking
    if tr is not None and tr.active:
        if cmd.flags & CMD_CTRL or not cmd.families:
            if cmd.flags & CMD_CTRL:
                tr.flush_all()
        else:
            try:
                tr.invalidate_key(as_bytes(args[0]) if args else b"")
            except CstError:
                tr.flush_all()
    rc = node.read_cache
    if not len(rc):
        return
    if cmd.flags & CMD_CTRL or not cmd.families:
        if cmd.flags & CMD_CTRL:
            rc.clear()
        return
    if args:
        try:
            key = as_bytes(args[0])
            stride = _MEMBER_WRITE_STRIDE.get(cmd.name) if scoped else None
            if stride is not None:
                rc.invalidate_key_members(
                    key, [as_bytes(a) for a in args[1::stride]])
            else:
                rc.invalidate_key(key)
            return
        except CstError:
            pass
    rc.clear()


def apply_replicated(node: "Node", name: bytes, args: list, origin_nodeid: int,
                     uuid: int) -> Msg:
    """Replication-path dispatch with the originator's identity
    (reference Cmd::exec_detail with repl=false, pull.rs:184-235)."""
    cmd = COMMANDS.get(name)
    if cmd is None:
        cmd = COMMANDS.get(name.lower())
        if cmd is None:
            raise UnknownCmd(name.decode("utf-8", "replace"))
    if cmd.flags & CMD_CLIENT_ONLY:
        raise InvalidRequestMsg(f"'{name.decode()}' cannot come from a replica")
    node.stats.cmds_replicated += 1
    node.ensure_flushed()
    node.hlc.observe(uuid)
    ctx = ExecCtx(uuid, origin_nodeid, True, None)
    if cmd.is_write:
        # replication intake invalidates BEFORE the op lands: a cached
        # hot-key reply must never outlive a peer's write to that key
        # (the per-frame twin of merge_batches' batched invalidation).
        # Member-scoping is safe pre-land: the op can only touch the
        # members it names, landed or not.
        _invalidate_read_cache(node, cmd, args, scoped=True)
    reply = cmd.handler(node, ctx, ArgIter(args, name))
    if cmd.is_write:
        node.ks.touch(*cmd.families)
    return reply


# ====================================================================
# generic commands (reference src/cmd.rs:141-346)
# ====================================================================

@serve_read("get", "get")
@register("get", CMD_READONLY)
def get_command(node, ctx, args):
    key = args.next_bytes()
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0 or not ks.alive(kid):
        return NIL
    enc = ks.enc_of(kid)
    if enc == S.ENC_COUNTER:
        return Int(ks.counter_sum(kid))
    if enc == S.ENC_BYTES:
        v = ks.register_get(kid)
        return Bulk(v if v is not None else b"")
    raise _invalid_type()


def _invalid_type():
    from ..errors import InvalidType
    return InvalidType()


@register("set", CMD_WRITE | CMD_DENYOOM, families=("env", "reg"))
def set_command(node, ctx, args):
    key = args.next_bytes()
    val = args.next_bytes()
    kid, _created = node.ks.get_or_create(key, S.ENC_BYTES, ctx.uuid)
    if node.ks.register_set(kid, val, ctx.uuid, ctx.nodeid):
        return OK
    return Int(0)  # stale write ignored (reference cmd.rs:199-201)


@register("desc", CMD_READONLY)
def desc_command(node, ctx, args):
    key = args.next_bytes()
    kid = node.ks.query(key, ctx.uuid)
    if kid < 0:
        return NIL
    d = node.ks.describe(kid)
    return Arr([Bulk(f"{k}: {v}") for k, v in d.items()])


@register("del", CMD_WRITE | CMD_NO_REPLICATE | CMD_CLIENT_ONLY, families=("env", "cnt", "el"))
def del_command(node, ctx, args):
    """Rewrites itself into type-specific REPL_ONLY tombstone commands
    (reference src/cmd.rs:220-296)."""
    key = args.next_bytes()
    ks = node.ks
    uuid = ctx.uuid
    kid = ks.query(key, uuid)
    if kid < 0:
        return Int(0)
    enc = ks.enc_of(kid)
    ct, mt, dt = ks.envelope(kid)
    deleted = 0
    if enc in (S.ENC_COUNTER, S.ENC_BYTES, S.ENC_TENSOR):
        # no deletion while unseen later modifications exist (reference
        # policy for client-originated deletes, cmd.rs:232-235)
        if mt <= uuid and ct >= dt:
            ks.keys.dt[kid] = uuid
            ks.keys.mt[kid] = uuid
            ks.record_key_delete(key, uuid)
            deleted = 1
            if enc == S.ENC_COUNTER:
                # record the observed totals as per-slot bases (absolute
                # assignments — the reference's negated-delta scheme,
                # cmd.rs:233-254, diverges when the delete and concurrent
                # increments interleave differently across replicas)
                rep = [Bulk(key)]
                for slot_node, total, _t, _b, _bt in ks.counter_slots(kid):
                    ks.counter_set_base(kid, slot_node, total, uuid)
                    rep.append(Int(slot_node))
                    rep.append(Int(total))
                node.replicate_cmd(uuid, b"delcnt", rep)
            elif enc == S.ENC_TENSOR:
                node.replicate_cmd(uuid, b"deltensor", [Bulk(key)])
            else:
                node.replicate_cmd(uuid, b"delbytes", [Bulk(key)])
    elif enc in _DEL_COLLECTION_CMD:
        members = [m for m, *_ in ks.elem_all(kid)]
        for m in members:
            ks.elem_rem(kid, m, uuid)
        if ct >= dt and uuid > ct:
            deleted = 1
        ks.set_delete_time(kid, uuid)
        ks.record_key_delete(key, uuid)
        node.replicate_cmd(uuid, _DEL_COLLECTION_CMD[enc], [Bulk(key)])
    return Int(deleted)


# element-plane encodings delete alike: tombstone every member + the key
_DEL_COLLECTION_CMD = {S.ENC_SET: b"delset", S.ENC_DICT: b"deldict",
                       S.ENC_MV: b"delmv", S.ENC_LIST: b"dellist"}


@register("delbytes", CMD_WRITE | CMD_REPL_ONLY | CMD_NO_REPLICATE | CMD_NO_REPLY, families=("env",))
def delbytes_command(node, ctx, args):
    key = args.next_bytes()
    ks = node.ks
    kid = ks.lookup(key)
    if kid < 0:
        # unlike the reference (cmd.rs:298-317 creates a LIVE empty key),
        # an unknown key materializes already-tombstoned: ct=0 < dt=uuid
        kid = ks.create_key(key, S.ENC_BYTES, 0)
    elif ks.enc_of(kid) != S.ENC_BYTES:
        raise _invalid_type()
    ks.set_delete_time(kid, ctx.uuid)
    ks.record_key_delete(key, ctx.uuid)
    return NO_REPLY


@register("node", CMD_CTRL)
def node_command(node, ctx, args):
    sub = args.next_bytes().lower()
    if sub == b"id":
        if not args.has_more:
            return Int(node.node_id)
        v = args.next_int()
        if v <= 0:
            return Err(b"id must be greater than 0")
        node.node_id = v
        return OK
    if sub == b"alias":
        if not args.has_more:
            return Bulk(node.alias.encode())
        node.alias = args.next_str()
        return OK
    return Err(b"unsupported command")


@register("repllog", CMD_CTRL)
def repllog_command(node, ctx, args):
    sub = args.next_str().lower()
    if sub == "at":
        e = node.repl_log.at(args.next_uint())
        return node.repl_log.entry_as_msg(e) if e else NIL
    if sub == "uuids":
        return Arr([Int(u) for u in node.repl_log.uuids()])
    raise UnknownSubCmd(sub, "REPLLOG")


@register("hello", CMD_CTRL)
def hello_command(node, ctx, args):
    """HELLO [protover] — RESP protocol negotiation (Redis 6 shape,
    flattened to a RESP2 key/value array either way).  `HELLO 3` arms
    RESP3 on the connection: the server may then write out-of-band push
    frames (server/tracking.py invalidation broadcasts).  Connections
    that never say HELLO 3 stay byte-exact RESP2 — no push frame is
    ever emitted toward them.  Dropping back to HELLO 2 turns tracking
    off first (a RESP2 stream cannot carry the pushes)."""
    c = ctx.client
    if args.has_more:
        try:
            ver = args.next_int()
        except CstError:
            return Err(b"NOPROTO unsupported protocol version")
        if ver not in (2, 3):
            return Err(b"NOPROTO unsupported protocol version")
        if c is not None:
            if ver == 2 and c.tracking:
                node.tracking.unsubscribe(c)
            c.resp3 = ver == 3
    proto = 3 if c is not None and c.resp3 else 2
    return Arr([Bulk(b"server"), Bulk(b"constdb"),
                Bulk(b"version"), Bulk(b"1"),
                Bulk(b"proto"), Int(proto),
                Bulk(b"id"), Int(c.cid if c is not None else 0),
                Bulk(b"mode"),
                Bulk(b"cluster" if node.cluster is not None
                     else b"standalone")])


@register("client", CMD_CTRL)
def client_command(node, ctx, args):
    sub = args.next_str().lower()
    if sub == "threadid":
        return Bulk(str(threading.get_ident()).encode())
    if sub == "id":
        # unique per-connection id (Redis CLIENT ID); 0 for executions
        # with no connection (tests, replication, internal)
        return Int(ctx.client.cid if ctx.client is not None else 0)
    if sub == "list":
        app = getattr(node, "app", None)
        conns = list(app.client_conns.values()) \
            if app is not None and getattr(app, "client_conns", None) \
            else ([ctx.client] if ctx.client is not None else [])
        lines = "".join(c.describe() + "\n"
                        for c in sorted(conns, key=lambda c: c.cid))
        return Bulk(lines.encode())
    if sub == "tracking":
        # CLIENT TRACKING on|off [BCAST] [PREFIX p]... (server/tracking.py)
        mode = args.next_str().lower()
        bcast = False
        prefixes: list = []
        while args.has_more:
            opt = args.next_str().lower()
            if opt == "bcast":
                bcast = True
            elif opt == "prefix":
                prefixes.append(args.next_bytes())
            else:
                raise UnknownSubCmd(opt, "CLIENT TRACKING")
        c = ctx.client
        if mode == "off":
            if c is not None and c.tracking:
                node.tracking.unsubscribe(c)
            return OK
        if mode != "on":
            raise UnknownSubCmd(mode, "CLIENT TRACKING")
        if c is None:
            return Err(b"CLIENT TRACKING requires a client connection")
        if not c.resp3:
            return Err(b"CLIENT TRACKING requires the RESP3 protocol "
                       b"(say HELLO 3 first)")
        if prefixes and not bcast:
            return Err(b"PREFIX requires BCAST mode")
        node.tracking.subscribe(c, bcast=bcast, prefixes=tuple(prefixes))
        return OK
    raise UnknownSubCmd(sub, "CLIENT")


# ====================================================================
# counter commands (reference src/type_counter.rs:142-205)
# ====================================================================

def _counter_step(node, ctx, args, delta: int) -> Msg:
    """INCR/DECR: bump the local slot's lifetime total and replicate the
    new ABSOLUTE total (idempotent LWW assignment on the wire — see
    KeySpace.counter_change).  An optional amount argument scales the
    step (Redis INCRBY/DECRBY folded in; the reference steps by exactly 1
    — type_counter.rs:169-189)."""
    key = args.next_bytes()
    if args.has_more:
        delta *= args.next_int()
    kid, _ = node.ks.get_or_create(key, S.ENC_COUNTER, ctx.uuid)
    v, total = node.ks.counter_change(kid, ctx.nodeid, delta, ctx.uuid)
    node.ks.updated_at(kid, ctx.uuid)
    if not ctx.from_repl:
        # locally-originated steps are undoable (CNTUNDO); replicated
        # ones are not ours to invert (single-writer slots)
        node.undo.record(ctx.uuid, key, delta)
    node.replicate_cmd(ctx.uuid, b"cntset", [Bulk(key), Int(total)])
    return Int(v)


@register("incr", CMD_WRITE | CMD_NO_REPLICATE | CMD_DENYOOM, families=("env", "cnt"))
def incr_command(node, ctx, args):
    return _counter_step(node, ctx, args, 1)


@register("decr", CMD_WRITE | CMD_NO_REPLICATE | CMD_DENYOOM, families=("env", "cnt"))
def decr_command(node, ctx, args):
    return _counter_step(node, ctx, args, -1)


@register("cntset", CMD_WRITE | CMD_REPL_ONLY | CMD_NO_REPLICATE | CMD_NO_REPLY, families=("env", "cnt"))
def cntset_command(node, ctx, args):
    """Replicated counter write: assign the originator's lifetime total."""
    key = args.next_bytes()
    total = args.next_int()
    kid, _ = node.ks.get_or_create(key, S.ENC_COUNTER, ctx.uuid)
    node.ks.counter_set_total(kid, ctx.nodeid, total, ctx.uuid)
    node.ks.updated_at(kid, ctx.uuid)
    return NO_REPLY


@register("cntundo", CMD_WRITE | CMD_NO_REPLICATE | CMD_CLIENT_ONLY | CMD_DENYOOM, families=("env", "cnt"))
def cntundo_command(node, ctx, args):
    """`CNTUNDO key [uuid]` — sound inverse-op undo for the PN-counter
    family only (PAPERS.md, "The Only Undoable CRDTs are Counters"):
    undo THIS node's counter op `uuid` (or, without one, its newest
    not-yet-undone local op on `key`) by applying the negated delta as a
    fresh write.  The inverse replicates as an ordinary absolute-total
    CNTSET, so it rides every negotiated fast path — coalesced apply,
    serve planning, the columnar wire, snapshots, digests — like any
    increment.  The undo is itself recorded, so undoing an undo redoes.
    Non-counter keys are rejected cleanly: no other family's ops admit a
    sound inverse (an element re-add is a NEW add, not an un-remove)."""
    key = args.next_bytes()
    uuid = args.next_uint() if args.has_more else None
    ks = node.ks
    kid = ks.lookup(key)
    if kid >= 0 and ks.enc_of(kid) != S.ENC_COUNTER:
        raise CstError("UNDO is only sound for counters "
                       "(arXiv 2006.10494); this key is not one")
    target = node.undo.resolve(key, uuid)
    if target is None:
        if uuid is not None and node.undo.known(uuid):
            raise CstError("op already undone or key mismatch")
        raise CstError("unknown, remote, or evicted counter op: only "
                       "this node's recent local steps are undoable")
    t_uuid, delta = target
    kid, _ = ks.get_or_create(key, S.ENC_COUNTER, ctx.uuid)
    v, total = ks.counter_change(kid, ctx.nodeid, -delta, ctx.uuid)
    ks.updated_at(kid, ctx.uuid)
    node.undo.mark_undone(t_uuid)
    node.undo.record(ctx.uuid, key, -delta, inverse=True)
    node.replicate_cmd(ctx.uuid, b"cntset", [Bulk(key), Int(total)])
    return Int(v)


@register("delcnt", CMD_WRITE | CMD_REPL_ONLY | CMD_NO_REPLICATE | CMD_NO_REPLY, families=("env", "cnt"))
def delcnt_command(node, ctx, args):
    """Counter delete: tombstone the key and assign each listed slot's
    delete-observed base (visible value becomes total - base)."""
    key = args.next_bytes()
    ks = node.ks
    kid = ks.lookup(key)
    if kid < 0:
        # materialize already-tombstoned (ct=0 < dt) so bases still register
        kid = ks.create_key(key, S.ENC_COUNTER, 0)
    elif ks.enc_of(kid) != S.ENC_COUNTER:
        raise _invalid_type()
    ks.set_delete_time(kid, ctx.uuid)
    ks.record_key_delete(key, ctx.uuid)
    while args.has_more:
        slot_node = args.next_uint()
        base = args.next_int()
        ks.counter_set_base(kid, slot_node, base, ctx.uuid)
    return NO_REPLY


# ====================================================================
# set commands (reference src/type_set.rs)
# ====================================================================

@register("sadd", CMD_WRITE | CMD_DENYOOM, families=("env", "el"))
def sadd_command(node, ctx, args):
    key = args.next_bytes()
    members = args.rest_bytes()
    if not members:
        raise WrongArity("sadd")
    ks = node.ks
    kid, _ = ks.get_or_create(key, S.ENC_SET, ctx.uuid)
    cnt = sum(ks.elem_add(kid, m, None, ctx.uuid, ctx.nodeid) for m in members)
    dt = int(ks.keys.dt[kid])
    if ctx.uuid < dt:
        # a concurrent key-level delete from another replica wins
        # (reference type_set.rs:35-39)
        for m in members:
            ks.elem_rem(kid, m, dt)
        cnt = 0
    ks.updated_at(kid, ctx.uuid)
    return Int(cnt)


@register("srem", CMD_WRITE, families=("env", "el"))
def srem_command(node, ctx, args):
    key = args.next_bytes()
    members = args.rest_bytes()
    if not members:
        raise WrongArity("srem")
    ks = node.ks
    kid, _ = ks.get_or_create(key, S.ENC_SET, ctx.uuid)
    cnt = sum(ks.elem_rem(kid, m, ctx.uuid) for m in members)
    ks.updated_at(kid, ctx.uuid)
    return Int(cnt)


@serve_read("smembers", "members", enc=S.ENC_SET)
@register("smembers", CMD_READONLY)
def smembers_command(node, ctx, args):
    key = args.next_bytes()
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0:
        return NIL
    if ks.enc_of(kid) != S.ENC_SET:
        raise _invalid_type()
    return Arr([Bulk(m) for m, _v, _t in ks.elem_live(kid)])


@serve_read("scnt", "card", enc=S.ENC_SET)
@register("scnt", CMD_READONLY)
def scnt_command(node, ctx, args):
    """SCNT key — live member count (the reference's set-cardinality
    probe; Redis SCARD).  Mirrors SMEMBERS' visibility exactly: the
    key-level tombstone is NOT consulted — a dead key's count is simply
    the count of its live members (normally 0, but add-wins members
    newer than the delete stay visible)."""
    key = args.next_bytes()
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0:
        return Int(0)
    if ks.enc_of(kid) != S.ENC_SET:
        raise _invalid_type()
    return Int(sum(1 for _ in ks.elem_live(kid)))


@serve_read("sismember", "ismember", enc=S.ENC_SET, arity=3)
@register("sismember", CMD_READONLY)
def sismember_command(node, ctx, args):
    """SISMEMBER key member — 1 iff the member is visible (same
    element-liveness rule as SMEMBERS, one combo probe instead of a
    full scan)."""
    key = args.next_bytes()
    member = args.next_bytes()
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0:
        return Int(0)
    if ks.enc_of(kid) != S.ENC_SET:
        raise _invalid_type()
    row = ks.el_row(kid, member)
    if row < 0:
        return Int(0)
    el = ks.el
    return Int(1 if S.elem_alive(int(el.add_t[row]), int(el.del_t[row]))
               else 0)


@register("spop", CMD_WRITE | CMD_NO_REPLICATE, families=("env", "el"))
def spop_command(node, ctx, args):
    key = args.next_bytes()
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0:
        return NIL
    if ks.enc_of(kid) != S.ENC_SET:
        raise _invalid_type()
    live = [m for m, _v, _t in ks.elem_live(kid)]
    if not live:
        return NIL
    member = live[random.randrange(len(live))]
    ks.elem_rem(kid, member, ctx.uuid)
    ks.updated_at(kid, ctx.uuid)
    # deterministic rewrite so every replica pops the SAME member
    node.replicate_cmd(ctx.uuid, b"srem", [Bulk(key), Bulk(member)])
    return Bulk(member)


def _del_collection(node, ctx, args, enc: int) -> Msg:
    key = args.next_bytes()
    ks = node.ks
    kid = ks.lookup(key)
    if kid < 0:
        kid = ks.create_key(key, enc, 0)
    elif ks.enc_of(kid) != enc:
        raise _invalid_type()
    for m, *_ in list(ks.elem_all(kid)):
        ks.elem_rem(kid, m, ctx.uuid)
    ks.set_delete_time(kid, ctx.uuid)
    ks.record_key_delete(key, ctx.uuid)
    return NO_REPLY


@register("delset", CMD_WRITE | CMD_REPL_ONLY | CMD_NO_REPLICATE | CMD_NO_REPLY, families=("env", "el"))
def delset_command(node, ctx, args):
    return _del_collection(node, ctx, args, S.ENC_SET)


# ====================================================================
# hash commands (reference src/type_hash.rs)
# ====================================================================

@register("hset", CMD_WRITE | CMD_DENYOOM, families=("env", "el"))
def hset_command(node, ctx, args):
    key = args.next_bytes()
    kvs = []
    while args.has_more:
        f = args.next_bytes()
        kvs.append((f, args.next_bytes()))
    if not kvs:
        raise WrongArity("hset")
    ks = node.ks
    kid, _ = ks.get_or_create(key, S.ENC_DICT, ctx.uuid)
    cnt = sum(ks.elem_add(kid, f, v, ctx.uuid, ctx.nodeid) for f, v in kvs)
    dt = int(ks.keys.dt[kid])
    if ctx.uuid < dt:
        # concurrent key-level delete wins (reference type_hash.rs:38-43)
        for f, _v in kvs:
            ks.elem_rem(kid, f, dt)
        cnt = 0
    ks.updated_at(kid, ctx.uuid)
    return Int(cnt)


@serve_read("hget", "elemget", enc=S.ENC_DICT, arity=3)
@register("hget", CMD_READONLY)
def hget_command(node, ctx, args):
    key = args.next_bytes()
    field = args.next_bytes()
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0:
        return NIL
    if ks.enc_of(kid) != S.ENC_DICT:
        raise _invalid_type()
    v = ks.elem_get(kid, field)
    return Bulk(v) if v is not None else NIL


@serve_read("hgetall", "pairs", enc=S.ENC_DICT)
@register("hgetall", CMD_READONLY)
def hgetall_command(node, ctx, args):
    key = args.next_bytes()
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0:
        return NIL
    if ks.enc_of(kid) != S.ENC_DICT:
        raise _invalid_type()
    return Arr([Arr([Bulk(f), Bulk(v if v is not None else b"")])
                for f, v, _t in ks.elem_live(kid)])


@serve_read("hlen", "card", enc=S.ENC_DICT)
@register("hlen", CMD_READONLY)
def hlen_command(node, ctx, args):
    """HLEN key — live field count (the hash twin of SCNT/LLEN; Redis
    HLEN).  Mirrors HGETALL's visibility exactly: the key-level
    tombstone is NOT consulted — a dead key's count is the count of its
    live fields (normally 0, but add-wins fields newer than the delete
    stay visible)."""
    key = args.next_bytes()
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0:
        return Int(0)
    if ks.enc_of(kid) != S.ENC_DICT:
        raise _invalid_type()
    return Int(sum(1 for _ in ks.elem_live(kid)))


@register("hdel", CMD_WRITE, families=("env", "el"))
def hdel_command(node, ctx, args):
    key = args.next_bytes()
    fields = args.rest_bytes()
    if not fields:
        raise WrongArity("hdel")
    ks = node.ks
    kid, _ = ks.get_or_create(key, S.ENC_DICT, ctx.uuid)
    cnt = sum(ks.elem_rem(kid, f, ctx.uuid) for f in fields)
    ks.updated_at(kid, ctx.uuid)
    return Int(cnt)


@register("deldict", CMD_WRITE | CMD_REPL_ONLY | CMD_NO_REPLICATE | CMD_NO_REPLY, families=("env", "el"))
def deldict_command(node, ctx, args):
    return _del_collection(node, ctx, args, S.ENC_DICT)


# ====================================================================
# multi-value register commands (capability completion: the reference
# advertises a MultiValueRegister — README.md:10 — but its VClock scaffold
# is wired to nothing, src/crdt/vclock.rs.  Siblings live as element rows
# whose member bytes are the write's canonical clock; dominated siblings
# are tombstoned by later writes and pruned at read time.)
# ====================================================================

def _mv_live(ks, kid):
    from ..crdt.multivalue import clock_from_bytes
    return [(m, v, clock_from_bytes(m)) for m, v, _t in ks.elem_live(kid)]


def _mv_apply(ks, kid, clock_bytes, wc, val, uuid, nodeid) -> None:
    """Insert the sibling and tombstone every live sibling the write's
    clock dominates — deterministic from the clocks alone, so replicas
    applying this replicated write converge."""
    live = _mv_live(ks, kid)
    ks.elem_add(kid, clock_bytes, val, uuid, nodeid)
    for m, _v, vc in live:
        if m != clock_bytes and wc.dominates(vc):
            ks.elem_rem(kid, m, uuid)
    dt = int(ks.keys.dt[kid])
    if uuid < dt:
        # concurrent key-level delete from another replica wins
        ks.elem_rem(kid, clock_bytes, dt)
    ks.updated_at(kid, uuid)


@register("mvset", CMD_WRITE | CMD_NO_REPLICATE | CMD_DENYOOM, families=("env", "el"))
def mvset_command(node, ctx, args):
    """MVSET key value [context-token].  The token (from MVGET) is the
    causal context the writer observed; writing with it supersedes exactly
    what was read.  Replicates as the positional `mvwrite`."""
    from ..crdt.multivalue import VClock, clock_from_bytes, clock_to_bytes

    key = args.next_bytes()
    val = args.next_bytes()
    token = args.next_bytes() if args.has_more else None
    ks = node.ks
    kid, _ = ks.get_or_create(key, S.ENC_MV, ctx.uuid)
    if token is not None:
        ctx_vc = clock_from_bytes(token)
    else:
        ctx_vc = VClock()
        for _m, _v, vc in _mv_live(ks, kid):
            ctx_vc = ctx_vc.merge(vc)
    wc = ctx_vc.bump(ctx.nodeid)
    wb = clock_to_bytes(wc)
    _mv_apply(ks, kid, wb, wc, val, ctx.uuid, ctx.nodeid)
    node.replicate_cmd(ctx.uuid, b"mvwrite", [Bulk(key), Bulk(wb), Bulk(val)])
    return Bulk(wb)


@register("mvwrite", CMD_WRITE | CMD_REPL_ONLY | CMD_NO_REPLICATE | CMD_NO_REPLY, families=("env", "el"))
def mvwrite_command(node, ctx, args):
    from ..crdt.multivalue import clock_from_bytes

    key = args.next_bytes()
    wb = args.next_bytes()
    val = args.next_bytes()
    ks = node.ks
    kid, _ = ks.get_or_create(key, S.ENC_MV, ctx.uuid)
    _mv_apply(ks, kid, wb, clock_from_bytes(wb), val, ctx.uuid, ctx.nodeid)
    return NO_REPLY


@register("mvget", CMD_READONLY)
def mvget_command(node, ctx, args):
    """-> [[sibling values...], context-token].  Concurrent writes all
    surface (Dynamo-style); pass the token to MVSET to supersede them."""
    from ..crdt.multivalue import VClock, clock_to_bytes, frontier_of

    key = args.next_bytes()
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0 or not ks.alive(kid):
        return NIL
    if ks.enc_of(kid) != S.ENC_MV:
        raise _invalid_type()
    live = frontier_of(_mv_live(ks, kid))
    token = VClock()
    for _m, _v, vc in live:
        token = token.merge(vc)
    return Arr([Arr([Bulk(v if v is not None else b"") for _m, v, _vc in live]),
                Bulk(clock_to_bytes(token))])


@register("delmv", CMD_WRITE | CMD_REPL_ONLY | CMD_NO_REPLICATE | CMD_NO_REPLY, families=("env", "el"))
def delmv_command(node, ctx, args):
    return _del_collection(node, ctx, args, S.ENC_MV)


# ====================================================================
# list commands (capability completion: the reference scaffolds an ordered
# list — src/crdt/list.rs — wired to nothing.  Entries live as element rows
# whose member bytes are LSEQ position ids; byte-lex member order IS list
# order, so reads sort live members and merges are the element merge.)
# ====================================================================

def _list_live(ks, kid) -> list:
    """[(pos_bytes, value)] in list order."""
    return sorted((m, v) for m, v, _t in ks.elem_live(kid))


def _list_kid(node, ctx, key, for_write: bool):
    ks = node.ks
    if for_write:
        kid, _ = ks.get_or_create(key, S.ENC_LIST, ctx.uuid)
        return kid
    kid = ks.query(key, ctx.uuid)
    if kid < 0 or not ks.alive(kid):
        return -1
    if ks.enc_of(kid) != S.ENC_LIST:
        raise _invalid_type()
    return kid


def _list_insert(node, ctx, key, index: int, values: list) -> int:
    """Insert `values` before live index `index` (clamped); returns the new
    live length.  Each insert replicates as the positional `lins`."""
    from ..crdt.sequence import pos_between_bytes

    ks = node.ks
    kid = _list_kid(node, ctx, key, for_write=True)
    live = _list_live(ks, kid)
    index = max(0, min(index, len(live)))
    lo = live[index - 1][0] if index > 0 else None
    hi = live[index][0] if index < len(live) else None
    rep = [Bulk(key)]
    dt = int(ks.keys.dt[kid])
    for v in values:
        pos = pos_between_bytes(lo, hi, ctx.nodeid)
        ks.elem_add(kid, pos, v, ctx.uuid, ctx.nodeid)
        if ctx.uuid < dt:
            ks.elem_rem(kid, pos, dt)
        rep.append(Bulk(pos))
        rep.append(Bulk(v))
        lo = pos  # subsequent values land after the one just placed
    ks.updated_at(kid, ctx.uuid)
    # ONE replicated frame for the whole insert (repl_log uuids are unique)
    node.replicate_cmd(ctx.uuid, b"lins", rep)
    return len(_list_live(ks, kid))


@register("linsert", CMD_WRITE | CMD_NO_REPLICATE | CMD_DENYOOM, families=("env", "el"))
def linsert_command(node, ctx, args):
    key = args.next_bytes()
    index = args.next_int()
    values = args.rest_bytes()
    if not values:
        raise WrongArity("linsert")
    return Int(_list_insert(node, ctx, key, index, values))


@register("lpush", CMD_WRITE | CMD_NO_REPLICATE | CMD_DENYOOM, families=("env", "el"))
def lpush_command(node, ctx, args):
    key = args.next_bytes()
    values = args.rest_bytes()
    if not values:
        raise WrongArity("lpush")
    # redis convention: LPUSH k a b c pushes one at a time to the HEAD, so
    # the list reads c, b, a.  _list_insert places values consecutively, so
    # feed it the reversed order.
    return Int(_list_insert(node, ctx, key, 0, list(reversed(values))))


@register("rpush", CMD_WRITE | CMD_NO_REPLICATE | CMD_DENYOOM, families=("env", "el"))
def rpush_command(node, ctx, args):
    key = args.next_bytes()
    values = args.rest_bytes()
    if not values:
        raise WrongArity("rpush")
    return Int(_list_insert(node, ctx, key, 1 << 40, values))


@register("lins", CMD_WRITE | CMD_REPL_ONLY | CMD_NO_REPLICATE | CMD_NO_REPLY, families=("env", "el"))
def lins_command(node, ctx, args):
    """Positional replicated insert: `lins key pos1 val1 [pos2 val2 ...]`."""
    key = args.next_bytes()
    ks = node.ks
    kid, _ = ks.get_or_create(key, S.ENC_LIST, ctx.uuid)
    dt = int(ks.keys.dt[kid])
    while args.has_more:
        pos = args.next_bytes()
        val = args.next_bytes()
        ks.elem_add(kid, pos, val, ctx.uuid, ctx.nodeid)
        if ctx.uuid < dt:
            ks.elem_rem(kid, pos, dt)
    ks.updated_at(kid, ctx.uuid)
    return NO_REPLY


@register("lrem", CMD_WRITE | CMD_NO_REPLICATE, families=("env", "el"))
def lrem_command(node, ctx, args):
    """LREM key index — delete the element at live index; replicates as the
    positional `lremat` so every replica removes the SAME element."""
    key = args.next_bytes()
    index = args.next_int()
    ks = node.ks
    kid = _list_kid(node, ctx, key, for_write=False)
    if kid < 0:
        return Int(0)
    live = _list_live(ks, kid)
    if not 0 <= index < len(live):
        return Int(0)
    pos = live[index][0]
    ks.elem_rem(kid, pos, ctx.uuid)
    ks.updated_at(kid, ctx.uuid)
    node.replicate_cmd(ctx.uuid, b"lremat", [Bulk(key), Bulk(pos)])
    return Int(1)


@register("lremat", CMD_WRITE | CMD_REPL_ONLY | CMD_NO_REPLICATE | CMD_NO_REPLY, families=("env", "el"))
def lremat_command(node, ctx, args):
    key = args.next_bytes()
    pos = args.next_bytes()
    ks = node.ks
    kid, _ = ks.get_or_create(key, S.ENC_LIST, ctx.uuid)
    ks.elem_rem(kid, pos, ctx.uuid)
    ks.updated_at(kid, ctx.uuid)
    return NO_REPLY


@serve_read("lrange", "lrange", enc=S.ENC_LIST, arity=4)
@register("lrange", CMD_READONLY)
def lrange_command(node, ctx, args):
    """LRANGE key start stop — redis-style inclusive range with negative
    indices."""
    key = args.next_bytes()
    start = args.next_int()
    stop = args.next_int()
    kid = _list_kid(node, ctx, key, for_write=False)
    if kid < 0:
        return Arr([])
    vals = [v for _m, v in _list_live(node.ks, kid)]
    n = len(vals)
    if start < 0:
        start += n
    if stop < 0:
        stop += n
    start = max(0, start)
    if stop < start:
        return Arr([])
    return Arr([Bulk(v if v is not None else b"")
                for v in vals[start:stop + 1]])


@serve_read("llen", "llen", enc=S.ENC_LIST)
@register("llen", CMD_READONLY)
def llen_command(node, ctx, args):
    key = args.next_bytes()
    kid = _list_kid(node, ctx, key, for_write=False)
    if kid < 0:
        return Int(0)
    return Int(len(_list_live(node.ks, kid)))


@register("dellist", CMD_WRITE | CMD_REPL_ONLY | CMD_NO_REPLICATE | CMD_NO_REPLY, families=("env", "el"))
def dellist_command(node, ctx, args):
    return _del_collection(node, ctx, args, S.ENC_LIST)


# ====================================================================
# tensor-valued registers (crdt/tensor.py — the two-layer CRDT of
# arXiv 2605.19373): dense float arrays whose merge is a per-node
# contributor-slot LWW and whose read is a registered strategy
# reduction in canonical (node, uuid) order.  Shape/dtype/strategy are
# FIXED at key creation; contributions replicate as the absolute
# rewrite `tset` (idempotent LWW assignment on the wire, like cntset).
# ====================================================================


def _tensor_error(e) -> CstError:
    return InvalidRequestMsg(str(e))


def _tensor_knobs() -> tuple[str, int]:
    from ..conf import env_int, env_str
    return (env_str("CONSTDB_TENSOR_STRATEGY", "lww"),
            env_int("CONSTDB_TENSOR_MAX_ELEMS", 1 << 22))


@register("tensor.set", CMD_WRITE | CMD_NO_REPLICATE | CMD_DENYOOM, families=("env", "tns"))
def tensor_set_command(node, ctx, args):
    """TENSOR.SET key strategy dtype shape payload [count] — create the
    key (fixing strategy/dtype/shape) and assign this node's
    contributor slot.  `strategy` may be `-` for the configured default
    (CONSTDB_TENSOR_STRATEGY); `shape` is `4096` or `64x64`; `payload`
    is the raw little-endian array bytes; `count` weights the `avg`
    strategy (default 1)."""
    from ..crdt import tensor as T

    key = args.next_bytes()
    strat_s = args.next_str()
    dtype_s = args.next_str()
    shape_s = args.next_str()
    payload = args.next_bytes()
    cnt = args.next_int() if args.has_more else 1
    default_strat, max_elems = _tensor_knobs()
    if node.ks.lookup(key) >= 0:
        # the size cap guards key CREATION only — config is
        # creation-fixed, so writes to an existing key must keep
        # working after the knob is lowered (README Tuning row)
        max_elems = 1 << 62
    try:
        T.check_count(cnt)
        meta = T.parse_meta(strat_s, dtype_s, shape_s,
                            default_strat=default_strat,
                            max_elems=max_elems)
        cfg = T.pack_config(meta)
        arr = T.payload_array(meta, payload)
        kid = node.ks.tensor_get_or_create(key, cfg, ctx.uuid)
    except T.TensorConfigError as e:
        raise _tensor_error(e) from None
    node.ks.tensor_count_merge(meta)
    node.ks.tensor_slot_set(kid, ctx.nodeid, ctx.uuid, cnt, arr)
    node.ks.updated_at(kid, ctx.uuid)
    node.replicate_cmd(ctx.uuid, b"tset",
                       [Bulk(key), Bulk(cfg), Int(cnt), Bulk(payload)])
    return OK


@register("tensor.merge", CMD_WRITE | CMD_NO_REPLICATE | CMD_DENYOOM, families=("env", "tns"))
def tensor_merge_command(node, ctx, args):
    """TENSOR.MERGE key payload [count] — contribute a payload to an
    EXISTING tensor key (the config came from its creation)."""
    from ..crdt import tensor as T

    key = args.next_bytes()
    payload = args.next_bytes()
    cnt = args.next_int() if args.has_more else 1
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0:
        raise InvalidRequestMsg("no such tensor key (TENSOR.SET creates)")
    if ks.enc_of(kid) != S.ENC_TENSOR:
        raise _invalid_type()
    meta = ks.tensor_meta_of(kid)
    if meta is None:
        # a tensor key can exist config-less (a replicated `deltensor`
        # for a never-seen key materializes the tombstoned row only):
        # without a creation-fixed config there is nothing to validate
        # the payload against — same error as an absent key
        raise InvalidRequestMsg("no such tensor key (TENSOR.SET creates)")
    try:
        T.check_count(cnt)
        arr = T.payload_array(meta, payload)
    except T.TensorConfigError as e:
        raise _tensor_error(e) from None
    ks.tensor_count_merge(meta)
    ks.tensor_slot_set(kid, ctx.nodeid, ctx.uuid, cnt, arr)
    ks.updated_at(kid, ctx.uuid)
    node.replicate_cmd(ctx.uuid, b"tset",
                       [Bulk(key), Bulk(T.pack_config(meta)), Int(cnt),
                        Bulk(payload)])
    return OK


@register("tensor.get", CMD_READONLY)
def tensor_get_command(node, ctx, args):
    """TENSOR.GET key — the strategy reduction over the live contributor
    set, as raw little-endian bytes (reshape client-side via STAT)."""
    key = args.next_bytes()
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0 or not ks.alive(kid):
        return NIL
    if ks.enc_of(kid) != S.ENC_TENSOR:
        raise _invalid_type()
    out = node.tensor_read(kid)  # device-first (resident pools)
    if out is None:
        return NIL
    return Bulk(out.tobytes())


@register("tensor.stat", CMD_READONLY)
def tensor_stat_command(node, ctx, args):
    """TENSOR.STAT key — config + contributor stamps: [strategy, dtype,
    shape, n_contributors, total_count, [node uuid count]...]."""
    from ..crdt import tensor as T

    key = args.next_bytes()
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0 or not ks.alive(kid):
        return NIL
    if ks.enc_of(kid) != S.ENC_TENSOR:
        raise _invalid_type()
    meta = ks.tensor_meta_of(kid)
    if meta is None:
        return NIL
    contribs = ks.tensor_contribs(kid)
    return Arr([
        Bulk(meta.strat_name.encode()),
        Bulk(T.DTYPE_NAMES[meta.dtype_code].encode()),
        Bulk("x".join(str(d) for d in meta.shape).encode()),
        Int(len(contribs)),
        Int(sum(c for _n, _u, c, _p in contribs)),
        Arr([Arr([Int(n_), Int(u), Int(c)])
             for n_, u, c, _p in contribs]),
    ])


@register("tset", CMD_WRITE | CMD_REPL_ONLY | CMD_NO_REPLICATE | CMD_NO_REPLY, families=("env", "tns"))
def tset_command(node, ctx, args):
    """Replicated tensor contribution: absolute (cfg, count, payload)
    assignment of the originator's slot at the frame uuid."""
    key = args.next_bytes()
    cfg = args.next_bytes()
    cnt = args.next_int()
    payload = args.next_bytes()
    kid, _created = node.ks.get_or_create(key, S.ENC_TENSOR, ctx.uuid)
    # snapshot-merge semantics on config/payload problems: log + skip
    # (tensor_merge_row), exactly like the engine paths
    node.ks.tensor_merge_row(kid, ctx.nodeid, ctx.uuid, cnt, cfg, payload)
    node.ks.updated_at(kid, ctx.uuid)
    return NO_REPLY


@register("deltensor", CMD_WRITE | CMD_REPL_ONLY | CMD_NO_REPLICATE | CMD_NO_REPLY, families=("env",))
def deltensor_command(node, ctx, args):
    """Tensor key delete: an envelope-level tombstone (add-wins — a
    later contribution resurrects the key with its full contributor
    set, like registers; slots are never swept)."""
    key = args.next_bytes()
    ks = node.ks
    kid = ks.lookup(key)
    if kid < 0:
        kid = ks.create_key(key, S.ENC_TENSOR, 0)
    elif ks.enc_of(kid) != S.ENC_TENSOR:
        raise _invalid_type()
    ks.set_delete_time(kid, ctx.uuid)
    ks.record_key_delete(key, ctx.uuid)
    return NO_REPLY


# ====================================================================
# expiry (capability completion: the reference ships the machinery with no
# command — SURVEY.md §"Known reference defects"; db.rs:53-71)
# ====================================================================

@register("expire", CMD_WRITE | CMD_NO_REPLICATE, families=("env",))
def expire_command(node, ctx, args):
    key = args.next_bytes()
    secs = args.next_uint()
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0 or not ks.alive(kid):
        return Int(0)
    exp_uuid = (now_ms() + secs * 1000) << SEQ_BITS
    ks.expire_at(key, exp_uuid)
    # replicate the ABSOLUTE expiry so replicas agree on the deadline
    node.replicate_cmd(ctx.uuid, b"expireat", [Bulk(key), Int(exp_uuid)])
    return Int(1)


@register("expireat", CMD_WRITE, families=("env",))
def expireat_command(node, ctx, args):
    key = args.next_bytes()
    exp_uuid = args.next_uint()
    ks = node.ks
    kid = ks.lookup(key)
    if kid < 0:
        return Int(0)
    ks.expire_at(key, exp_uuid)
    return Int(1)


@register("ttl", CMD_READONLY)
def ttl_command(node, ctx, args):
    key = args.next_bytes()
    ks = node.ks
    kid = ks.query(key, ctx.uuid)
    if kid < 0 or not ks.alive(kid):
        return Int(-2)
    exp = int(ks.keys.expire[kid])
    if exp == 0:
        return Int(-1)
    return Int(max(0, (exp >> SEQ_BITS) - now_ms()) // 1000)


# ====================================================================
# columnar encoders — the steady-state coalescing seam
# (replica/coalesce.py).  Each encoder translates ONE replicated frame
# into rows of the same columnar plane layout the snapshot writer
# serializes (persist/snapshot.py _encode_batch over engine/base.py
# ColumnarBatch), so a run of peer frames can fold through the batched
# merge engine instead of the per-key op path.  Only commands whose op
# handler is a pure pointwise CRDT merge are encodable — everything
# else (deletes, expiry, membership, MV sibling pruning) stays on the
# exact per-key path as a coalescer BARRIER.  An encoder raising
# NotColumnar or any CstError makes the coalescer fall back to that
# same per-key path, so error behavior is byte-identical too.
#
# This table is ALSO the batch wire protocol's vocabulary: the push
# loop group-encodes runs of consecutive entries whose names appear
# here into REPLBATCH frames (replica/wire.py), and the wire codec
# re-derives every envelope column from the row patterns these
# encoders emit.  A new encoder whose rows fall outside those patterns
# still replicates correctly — the codec demotes its runs to per-frame
# frames, loudly — but extend replica/wire.py alongside it to keep the
# batched path's coverage.
# ====================================================================

class NotColumnar(Exception):
    """This frame cannot ride the columnar fast path; apply per-key."""


COLUMNAR_ENCODERS: dict[bytes, Callable] = {}

# Barrier scoping for the coalescer's NON-encodable frames.  A frame in
# KEY_SCOPED_BARRIERS reads/sweeps live state of exactly the key in its
# first argument (collection-delete member sweeps, expireat's
# exists-check, mvwrite's sibling pruning) — it must flush the pending
# batch ONLY when that key has pending rows; otherwise it commutes with
# the whole batch and applies per-key without landing it.  STATE_FREE
# frames never touch the keyspace at all (membership).  Everything else
# non-encodable flushes unconditionally (unknown semantics).
KEY_SCOPED_BARRIERS = frozenset(
    (b"delset", b"deldict", b"delmv", b"dellist", b"expireat", b"mvwrite"))
STATE_FREE_BARRIERS = frozenset((b"meet", b"forget"))

# Tensor reads skip execute()'s blanket flush via READ_FLUSH_FAMILIES
# (defined with the read-plane tables near the top of this module):
# everything they read is env (narrow-flushed) or host-authoritative
# tensor stamps, and TENSOR.GET reduces from the resident device pools
# (Node.tensor_read) — the family's whole point is that reads do not
# force payload round-trips.  The scalar read families narrow the same
# way now (round 18).


def columnar(name: str):
    """Register `fn(builder, recs)` as the columnar GROUP encoder for the
    command registered under `name`.  `recs` is the coalescer's buffered
    run of frames for that command — tuples `(key, origin, uuid, items)`
    with `items` the RAW wire frame — and the encoder turns the whole
    run into columnar rows with C-speed list comprehensions (the
    per-frame python this replaces was the measured ceiling of the
    steady-state pull path).

    Contract: encoders PARSE BEFORE MUTATING the builder — every raise
    must happen before the first builder mutation, so a failing run
    leaves the batch untouched and the coalescer can retry rec-by-rec,
    barrier-replaying only the genuinely malformed frames (which then
    raise the exact op-path error).  Even a contract slip is safe:
    every encodable write is an idempotent merge, so a replay over
    half-encoded rows converges."""
    def deco(fn):
        assert name.encode() in COMMANDS, name
        COLUMNAR_ENCODERS[name.encode()] = fn
        return fn
    return deco


@columnar("set")
def _enc_set(bb, recs: list) -> None:
    # op twin: get_or_create + register_set (LWW) + updated_at-on-win;
    # the unconditional envelope max is identical because ct >= rv_t
    # holds invariantly, so a losing write's max(ct, uuid) is a no-op
    vals = [v if type(v := r[3][6]) is bytes else as_bytes(v)
            for r in recs]
    uuids = [r[2] for r in recs]
    ki0 = bb.add_keys([r[0] for r in recs], S.ENC_BYTES, uuids)
    bb.reg_run(ki0, uuids, [r[1] for r in recs], vals)


@columnar("cntset")
def _enc_cntset(bb, recs: list) -> None:
    rows = [(r[1], as_int(r[3][6]), r[2]) for r in recs]  # (node, tot, u)
    ki0 = bb.add_keys([r[0] for r in recs], S.ENC_COUNTER,
                      [r[2] for r in recs])
    bb.cnt_rows.extend(
        (ki0 + i, node, total, u, 0, S.NEUTRAL_T)
        for i, (node, total, u) in enumerate(rows))
    bb.n_rows += len(rows)


# sadd (valueless members) / hset / lins (member+value pairs): element
# add-side LWW writes.  `dt_check=True` marks the rows for the
# coalescer's flush-time key-delete rule (op twin: `if uuid <
# keys.dt[kid]: elem_rem(member, dt)` — evaluated against the LIVE dt
# when the batch lands, which is when the per-key path would have
# evaluated it had the frames applied there).

def _members_of(items: list) -> list:
    if len(items) < 7:
        raise NotColumnar("bad arity")  # the handler raises WrongArity
    if type(items[6]) is bytes:
        # raw-scanned replay record (persist/oplog.py scan raw mode):
        # arguments are plain bytes, all-or-nothing — skip the
        # coercion map on the replay hot path
        return list(items[6:])
    return list(map(as_bytes, items[6:]))


def _genc_elem_adds(bb, recs, enc, with_vals: bool) -> None:
    if with_vals:
        pairs = []
        for r in recs:
            it = r[3]
            if len(it) < 8 or len(it) & 1:
                raise NotColumnar("bad arity")
            if type(it[6]) is bytes:   # raw-scanned: all-bytes args
                pairs.append((list(it[6::2]), list(it[7::2])))
            else:
                pairs.append((list(map(as_bytes, it[6::2])),
                              list(map(as_bytes, it[7::2]))))
    else:
        pairs = [(_members_of(r[3]), None) for r in recs]
    ki0 = bb.add_keys([r[0] for r in recs], enc, [r[2] for r in recs])
    el = bb.el_rows
    n = 0
    for i, r in enumerate(recs):
        m, v = pairs[i]
        el.append((ki0 + i, m, v, r[2], r[1], 0, True))
        n += len(m)
    bb.n_rows += n
    if with_vals:
        bb._el_has_vals = True


@columnar("sadd")
def _enc_sadd(bb, recs):
    _genc_elem_adds(bb, recs, S.ENC_SET, with_vals=False)


@columnar("hset")
def _enc_hset(bb, recs):
    _genc_elem_adds(bb, recs, S.ENC_DICT, with_vals=True)


@columnar("lins")
def _enc_lins(bb, recs):
    _genc_elem_adds(bb, recs, S.ENC_LIST, with_vals=True)


# srem/hdel/lremat: del-side max.  A missing member row materializes
# with add_t=0/add_node=0 on both paths (KeySpace.elem_rem vs the
# engine's neutral-row creation), so encoding (0, 0, uuid) is exact.

def _genc_elem_rems(bb, recs, enc) -> None:
    members = [_members_of(r[3]) for r in recs]
    ki0 = bb.add_keys([r[0] for r in recs], enc, [r[2] for r in recs])
    el = bb.el_rows
    n = 0
    for i, r in enumerate(recs):
        m = members[i]
        el.append((ki0 + i, m, None, 0, 0, r[2], False))
        n += len(m)
    bb.n_rows += n


@columnar("srem")
def _enc_srem(bb, recs):
    _genc_elem_rems(bb, recs, S.ENC_SET)


@columnar("hdel")
def _enc_hdel(bb, recs):
    _genc_elem_rems(bb, recs, S.ENC_DICT)


@columnar("lremat")
def _enc_lremat(bb, recs):
    poss = [(as_bytes(r[3][6]),) for r in recs]
    ki0 = bb.add_keys([r[0] for r in recs], S.ENC_LIST,
                      [r[2] for r in recs])
    bb.el_rows.extend(
        (ki0 + i, poss[i], None, 0, 0, r[2], False)
        for i, r in enumerate(recs))
    bb.n_rows += len(recs)


# Scalar DELETE rewrites coalesce too: delbytes/delcnt are pure
# tombstone + LWW-pair writes, so they commute with everything a pending
# batch can hold (unlike the collection deletes delset/deldict/delmv/
# dellist, whose member sweep READS live rows — those stay barriers).

@columnar("delbytes")
def _enc_delbytes(bb, recs) -> None:
    bb.add_del_keys([r[0] for r in recs], S.ENC_BYTES,
                    [r[2] for r in recs])


@columnar("tset")
def _enc_tset(bb, recs) -> None:
    """Tensor contributions: pure slot LWW assignments — they commute
    with everything a pending batch can hold.  Payloads stay raw bytes
    in the batch (the engine normalizes via the row's cfg at merge)."""
    rows = [(as_bytes(r[3][6]), as_int(r[3][7]), as_bytes(r[3][8]))
            for r in recs]  # (cfg, cnt, payload) — parse before mutate
    ki0 = bb.add_keys([r[0] for r in recs], S.ENC_TENSOR,
                      [r[2] for r in recs])
    bb.tns_rows.extend(
        (ki0 + i, r[1], r[2], cnt, cfg, payload)
        for i, (r, (cfg, cnt, payload)) in enumerate(zip(recs, rows)))
    bb.n_rows += len(rows)


@columnar("deltensor")
def _enc_deltensor(bb, recs) -> None:
    bb.add_del_keys([r[0] for r in recs], S.ENC_TENSOR,
                    [r[2] for r in recs])


@columnar("delcnt")
def _enc_delcnt(bb, recs) -> None:
    """Counter delete: key tombstone + each listed slot's delete-observed
    base as an LWW assignment (base @ delete-uuid); the slot's total
    pair rides along neutral (val=0 @ NEUTRAL_T never beats a written
    slot, and ties with an unwritten one at its own value)."""
    slot_runs = []
    for r in recs:
        it = r[3]
        if len(it) & 1:
            raise NotColumnar("bad arity")  # key + (node, base) pairs
        pairs = []
        for i in range(6, len(it), 2):
            node = as_int(it[i])
            if node < 0:
                raise NotColumnar("bad node id")  # handler uses next_uint
            pairs.append((node, as_int(it[i + 1])))
        slot_runs.append(pairs)
    ki0 = bb.add_del_keys([r[0] for r in recs], S.ENC_COUNTER,
                          [r[2] for r in recs])
    for i, r in enumerate(recs):
        for node, base in slot_runs[i]:
            bb.cnt_rows.append((ki0 + i, node, 0, S.NEUTRAL_T, base, r[2]))
            bb.n_rows += 1


# ====================================================================
# serve planners — the client-path coalescing seam (server/serve.py).
# Pipelined client chunks are planned instead of executed per message:
# each planner below translates ONE client command into (a) its
# replication rewrite — buffered for the columnar GROUP encoders above
# and for repl_log.push_many — and (b) its reply, computed from the
# landed store plus the pending run's tracked deltas (which is exactly
# the state the per-command path would have seen, because the run lands
# before anything else can read it: reads and non-plannable commands
# are ordered barriers that flush first, and the whole chunk runs
# synchronously on the single-writer loop).  Only commands whose
# handler is a pure pointwise CRDT write with a reply derivable from
# (pre-state, args) are plannable; everything else — reads, DEL and the
# other read-modify rewrites, expiry, membership, admin — executes on
# the exact per-command path as a barrier.
# ====================================================================

SERVE_PLANNERS: dict[bytes, Callable] = {}

# --------------------------------------------------------------------
# shard routing classification (server/serve_shards.py).  Every DATA
# command's keyspace effects are confined to the key in its FIRST
# argument — the convention PR 5's barrier scoping already relies on
# and the KEY-CONFINED lint rule (constdb_tpu/analysis/rules.py) pins
# statically for the planner/encoder families.  Commands that touch
# GLOBAL state instead (membership, admin/CTRL, observability) execute
# on the parent's ordered barrier plane.  `PLANE_COMMANDS` lists the
# keyless non-CTRL commands structurally indistinguishable from data
# commands (their `families` default to ALL); `shard_routable` is the
# one classifier both the client router and the replication-apply
# router consult.
# --------------------------------------------------------------------

PLANE_COMMANDS = frozenset((b"info", b"replicas", b"meet", b"forget"))


def shard_routable(cmd: Command) -> bool:
    """True iff this command executes inside the shard worker owning
    its first-argument key; False = ordered barrier plane (parent)."""
    return not (cmd.flags & CMD_CTRL) and bool(cmd.families) \
        and cmd.name not in PLANE_COMMANDS

# Flush-time group encoders for the serve path: `fn(bb, recs, nodeid)`
# over the compact per-command records the planners buffered.  Unlike
# the replication COLUMNAR_ENCODERS (which parse raw wire frames at
# flush), these receive arguments the planner ALREADY coerced during
# validation — flush is pure C-speed list comprehension, no re-parse,
# and nothing here can raise on a planner-built record.  Row layouts
# are identical to the replication encoders', with one deliberate
# difference: element adds carry dt_check=False — a client write's
# fresh HLC uuid is strictly newer than any landed key-delete time (the
# clock has observed every landed write), and barriers flush before
# anything can raise a pending key's dt, so the flush-time key-delete
# rule is provably inert and its batched dt lookup is skipped.
SERVE_ENCODERS: dict[bytes, Callable] = {}


def _senc_set(bb, recs, nodeid):
    uuids = [r[1] for r in recs]
    ki0 = bb.add_keys([r[0] for r in recs], S.ENC_BYTES, uuids)
    bb.reg_run(ki0, uuids, [nodeid] * len(recs), [r[2] for r in recs])


def _senc_cntset(bb, recs, nodeid):
    ki0 = bb.add_keys([r[0] for r in recs], S.ENC_COUNTER,
                      [r[1] for r in recs])
    bb.cnt_rows.extend((ki0 + i, nodeid, r[2], r[1], 0, S.NEUTRAL_T)
                       for i, r in enumerate(recs))
    bb.n_rows += len(recs)


def _senc_elem_adds(enc: int, with_vals: bool):
    def enc_fn(bb, recs, nodeid):
        ki0 = bb.add_keys([r[0] for r in recs], enc, [r[1] for r in recs])
        el = bb.el_rows
        n = 0
        for i, r in enumerate(recs):
            el.append((ki0 + i, r[2], r[3] if with_vals else None,
                       r[1], nodeid, 0, False))
            n += len(r[2])
        bb.n_rows += n
        if with_vals:
            bb._el_has_vals = True
    return enc_fn


def _senc_elem_rems(enc: int):
    def enc_fn(bb, recs, nodeid):
        ki0 = bb.add_keys([r[0] for r in recs], enc, [r[1] for r in recs])
        el = bb.el_rows
        n = 0
        for i, r in enumerate(recs):
            el.append((ki0 + i, r[2], None, 0, 0, r[1], False))
            n += len(r[2])
        bb.n_rows += n
    return enc_fn


def _senc_tset(bb, recs, nodeid):
    ki0 = bb.add_keys([r[0] for r in recs], S.ENC_TENSOR,
                      [r[1] for r in recs])
    bb.tns_rows.extend((ki0 + i, nodeid, r[1], r[3], r[2], r[4])
                       for i, r in enumerate(recs))
    bb.n_rows += len(recs)


SERVE_ENCODERS[b"set"] = _senc_set
SERVE_ENCODERS[b"cntset"] = _senc_cntset
SERVE_ENCODERS[b"tset"] = _senc_tset
SERVE_ENCODERS[b"sadd"] = _senc_elem_adds(S.ENC_SET, with_vals=False)
SERVE_ENCODERS[b"hset"] = _senc_elem_adds(S.ENC_DICT, with_vals=True)
SERVE_ENCODERS[b"srem"] = _senc_elem_rems(S.ENC_SET)
SERVE_ENCODERS[b"hdel"] = _senc_elem_rems(S.ENC_DICT)

# Reads that observe exactly the key in their first argument (and touch
# no global state — not the repl_log, not membership, not stats).  With
# a run pending, such a read is a NON-FLUSHING barrier when its key has
# no pending rows: it commutes with every buffered write, so it may
# execute per-command in place while the run keeps filling — the serve
# twin of the replication coalescer's KEY_SCOPED_BARRIERS.  Anything
# else non-plannable flushes first (writes also push the repl_log,
# whose uuids must stay ordered with the pending run's).
SERVE_KEY_SCOPED_READS = frozenset(
    (b"get", b"smembers", b"scnt", b"sismember", b"hget", b"hgetall",
     b"lrange", b"llen", b"ttl", b"desc", b"mvget", b"tensor.get",
     b"tensor.stat"))

_INT0 = Int(0)


def serve_plan(name: str):
    """Register `fn(coal, items) -> Msg | None` as the serve-path planner
    for the client command `name` (`items` = the raw client frame,
    `[name, args...]`; `coal` = the connection's ServeCoalescer).  A
    planner either buffers the command's replication rewrite into the
    pending run and returns the reply, or returns None to DEMOTE the
    command to the exact per-command path (arity/coercion errors, type
    conflicts — node.execute raises the exact op error there).

    Contract (the planner twin of the encoders' parse-then-mutate rule):
    every demotion happens BEFORE the first mutation of coalescer state
    or the node HLC — a demoted command re-executes on the per-command
    path, which must mint the next uuid itself and see the store exactly
    as if the planner had never looked."""
    def deco(fn):
        cmd = COMMANDS[name.encode()]
        assert cmd.is_write and not (cmd.flags & CMD_REPL_ONLY), name
        SERVE_PLANNERS[cmd.name] = fn
        return fn
    return deco


@serve_plan("set")
def _plan_set(coal, items):
    # op twin: get_or_create + register_set (LWW) + replicate verbatim.
    # The win test runs against the pending run's register state when the
    # key was already written this run, else the landed (rv_t, rv_node) —
    # a fresh client uuid beats both in practice (the HLC has observed
    # every landed write), but the comparison stays exact regardless.
    if len(items) < 3:
        return None
    try:
        key = as_bytes(items[1])
        val = as_bytes(items[2])
    except CstError:
        return None
    kid = coal.resolve_key(key, S.ENC_BYTES)
    if kid is coal.CONFLICT:
        return None
    uuid = coal.tick()
    st = coal.regs.get(key)
    if st is None:
        st = (int(coal.ks.keys.rv_t[kid]), int(coal.ks.keys.rv_node[kid])) \
            if kid >= 0 else (0, 0)
    won = not S.lww_wins(st[0], st[1], uuid, coal.nodeid)
    if won:
        coal.regs[key] = (uuid, coal.nodeid)
    coal.add(b"set", (key, uuid, val), items[1:])
    return OK if won else _INT0


def _plan_counter_step(coal, items, sign):
    # op twin: _counter_step — bump our slot's lifetime total, reply the
    # new visible sum, replicate the ABSOLUTE total as `cntset`.  Both
    # numbers need the pre-run state once per key (landed sum + our
    # slot's landed total); later steps in the run are dict arithmetic.
    if len(items) < 2:
        return None
    try:
        key = as_bytes(items[1])
        delta = sign if len(items) < 3 else sign * as_int(items[2])
    except CstError:
        return None
    kid = coal.resolve_key(key, S.ENC_COUNTER)
    if kid is coal.CONFLICT:
        return None
    uuid = coal.tick()
    st = coal.cnts.get(key)
    if st is None:
        ks = coal.ks
        st = [ks.counter_sum(kid),
              ks.counter_slot_total(kid, coal.nodeid)] if kid >= 0 \
            else [0, 0]
        coal.cnts[key] = st
    st[0] += delta
    st[1] += delta
    coal.node.undo.record(uuid, key, delta)  # the op twin's CNTUNDO hook
    coal.add(b"cntset", (key, uuid, st[1]), [items[1], Int(st[1])])
    return Int(st[0])


@serve_plan("incr")
def _plan_incr(coal, items):
    return _plan_counter_step(coal, items, 1)


@serve_plan("decr")
def _plan_decr(coal, items):
    return _plan_counter_step(coal, items, -1)


@serve_plan("cntundo")
def _plan_cntundo(coal, items):
    # op twin: cntundo_command — the inverse step is just a counter step
    # whose delta comes from the undo log, so it plans exactly like
    # INCR/DECR once the target resolves.  Every rejection (non-counter
    # key, unknown/undone/evicted op) demotes BEFORE any mutation, and
    # the per-command path raises the exact error.
    n = len(items)
    if n < 2 or n > 3:
        return None
    try:
        key = as_bytes(items[1])
        uuid = as_uint(items[2]) if n > 2 else None
    except CstError:
        return None
    kid = coal.resolve_key(key, S.ENC_COUNTER)
    if kid is coal.CONFLICT:
        return None
    undo = coal.node.undo
    target = undo.resolve(key, uuid)
    if target is None:
        return None  # exact op error per-command
    t_uuid, delta = target
    new_uuid = coal.tick()
    st = coal.cnts.get(key)
    if st is None:
        ks = coal.ks
        st = [ks.counter_sum(kid),
              ks.counter_slot_total(kid, coal.nodeid)] if kid >= 0 \
            else [0, 0]
        coal.cnts[key] = st
    st[0] -= delta
    st[1] -= delta
    undo.mark_undone(t_uuid)
    undo.record(new_uuid, key, -delta, inverse=True)
    coal.add(b"cntset", (key, new_uuid, st[1]), [items[1], Int(st[1])])
    return Int(st[0])


def _plan_elem_update(coal, items, name, enc, add):
    # op twin: sadd/srem — the reply counts members whose VISIBILITY
    # flipped (elem_add/elem_rem return values), evaluated against the
    # landed element rows overlaid with the run's tracked flips.  A
    # fresh client uuid always wins the add-side LWW and the del-side
    # max, so visibility after the op is simply `add`.
    if len(items) < 3:
        return None
    try:
        key = as_bytes(items[1])
        members = [as_bytes(m) for m in items[2:]]
    except CstError:
        return None
    kid = coal.resolve_key(key, enc)
    if kid is coal.CONFLICT:
        return None
    uuid = coal.tick()
    cnt = coal.count_elem_flips(key, kid, members, add)
    coal.add(name, (key, uuid, members), items[1:])
    return Int(cnt)


@serve_plan("sadd")
def _plan_sadd(coal, items):
    return _plan_elem_update(coal, items, b"sadd", S.ENC_SET, True)


@serve_plan("srem")
def _plan_srem(coal, items):
    return _plan_elem_update(coal, items, b"srem", S.ENC_SET, False)


@serve_plan("hdel")
def _plan_hdel(coal, items):
    return _plan_elem_update(coal, items, b"hdel", S.ENC_DICT, False)


def _plan_tensor_common(coal, items, key, cfg, meta, payload, cnt):
    """Shared tail of the tensor planners (callers hold the validated
    meta): the payload-size check is the last demote gate; everything
    after mutates (tick + buffer)."""
    if len(payload) != meta.nbytes:
        return None  # per-command path raises the exact op error
    uuid = coal.tick()
    coal.add(b"tset", (key, uuid, cfg, cnt, payload),
             [items[1], Bulk(cfg), Int(cnt), Bulk(payload)])
    return OK


@serve_plan("tensor.set")
def _plan_tensor_set(coal, items):
    # op twin: tensor_set_command — config parse/validation and the
    # payload-size check all demote (the per-command path raises the
    # exact error); a run-created key's config lands in the run overlay
    # (coal.tns) so later SET/MERGE in the same run validate against it
    from ..crdt import tensor as T
    n = len(items)
    if n < 6 or n > 7:
        return None
    try:
        key = as_bytes(items[1])
        strat_s = as_bytes(items[2]).decode("utf-8", "replace")
        dtype_s = as_bytes(items[3]).decode("utf-8", "replace")
        shape_s = as_bytes(items[4]).decode("utf-8", "replace")
        payload = as_bytes(items[5])
        cnt = as_int(items[6]) if n > 6 else 1
    except CstError:
        return None
    if cnt < 1:
        return None  # per-command path raises the exact count error
    default_strat, max_elems = _tensor_knobs()
    try:
        # cap applied below, only when the key is genuinely NEW — the
        # op twin exempts existing keys (config is creation-fixed)
        meta = T.parse_meta(strat_s, dtype_s, shape_s,
                            default_strat=default_strat,
                            max_elems=1 << 62)
    except T.TensorConfigError:
        return None
    cfg = T.pack_config(meta)
    kid = coal.resolve_key(key, S.ENC_TENSOR)
    if kid is coal.CONFLICT:
        return None
    if kid < 0 and key not in coal.tns and meta.elems > max_elems:
        return None  # new key over the cap: exact op error per-command
    if kid >= 0:
        landed = coal.ks.tensor_meta_of(kid)
        if landed is None or T.pack_config(landed) != cfg:
            return None  # config mismatch: exact op error per-command
    else:
        prev = coal.tns.get(key)
        if prev is not None and prev != cfg:
            return None
        if len(payload) != meta.nbytes:
            return None  # demote BEFORE recording the run overlay
        coal.tns[key] = cfg
    return _plan_tensor_common(coal, items, key, cfg, meta, payload, cnt)


@serve_plan("tensor.merge")
def _plan_tensor_merge(coal, items):
    # op twin: tensor_merge_command — the key must already exist as a
    # tensor (landed, or created earlier in this run)
    from ..crdt import tensor as T
    n = len(items)
    if n < 3 or n > 4:
        return None
    try:
        key = as_bytes(items[1])
        payload = as_bytes(items[2])
        cnt = as_int(items[3]) if n > 3 else 1
    except CstError:
        return None
    if cnt < 1:
        return None  # per-command path raises the exact count error
    kid = coal.resolve_key(key, S.ENC_TENSOR)
    if kid is coal.CONFLICT:
        return None
    if kid >= 0:
        meta = coal.ks.tensor_meta_of(kid)
        if meta is None:
            return None
        cfg = T.pack_config(meta)
    else:
        cfg = coal.tns.get(key)
        if cfg is None:
            return None  # absent key: exact no-such-key error
        meta = T.unpack_config(cfg)
    return _plan_tensor_common(coal, items, key, cfg, meta, payload, cnt)


@serve_plan("hset")
def _plan_hset(coal, items):
    # op twin: hset — reply counts fields that became visible; values
    # ride the add-side LWW (overwriting a live field counts 0).
    n = len(items)
    if n < 4 or n & 1:
        return None  # key + (field, value) pairs — WrongArity otherwise
    try:
        key = as_bytes(items[1])
        fields = [as_bytes(f) for f in items[2::2]]
        vals = [as_bytes(v) for v in items[3::2]]
    except CstError:
        return None
    kid = coal.resolve_key(key, S.ENC_DICT)
    if kid is coal.CONFLICT:
        return None
    uuid = coal.tick()
    cnt = coal.count_elem_flips(key, kid, fields, True)
    coal.add(b"hset", (key, uuid, fields, vals), items[1:])
    return Int(cnt)


# membership + observability commands register themselves against this table
from ..replica import commands as _replica_commands  # noqa: E402,F401
from . import info as _info_commands  # noqa: E402,F401
from ..cluster import commands as _cluster_commands  # noqa: E402,F401
