"""Versioned hot-key reply cache: finished RESP reply bytes by key.

Canonical cache traffic is read-dominated, and a CRDT read is a pure
function of converged state (PAPERS.md: Approaches to CRDTs — queries
never mutate merge state), which makes its finished reply *cacheable by
version*: the serve coalescer's read planner (server/serve.py) stores
the encoded reply bytes of a key-scoped read here under
`(command, key, args-digest)` and replays them verbatim while the key's
state is provably unchanged.

Two mechanisms keep a cached reply exact, and both must hold
(docs/INVARIANTS.md "Read coalescing laws"):

  * **invalidate-before-visible** — every mutation intake drops the
    written keys' entries BEFORE the mutation becomes readable: the
    client op path (`commands.execute`), the per-frame replication path
    (`commands.apply_replicated`), and every batched merge — serve
    coalescer flushes, coalesced replication apply, columnar wire
    batches, snapshot/delta ingest, oplog replay — via the one engine
    seam they all ride (`Node.merge_batch`/`merge_batches`).  Sharded
    nodes hold one cache per shard worker (the worker's Node owns it),
    so each worker invalidates exactly its own shard.  State wipes
    (full resync) clear the cache outright.
  * **envelope stamp** — each entry records the key's envelope
    `(ct, mt, dt, expire)` at fill time and is served only while the
    live envelope still matches (expiry-armed keys are never cached at
    all — their replies are time-dependent).  Member-scoped kinds
    (sismember/hget — reply reads ONE element) skip the ct/mt checks
    (stored as -1): EVERY element write advances both (updated_at's max
    rule) while touching only the members it names, and those members'
    entries are exactly what the member-scoped intake hooks drop
    (`invalidate_key_members`); dt/expire still verify, so key
    delete/expiry always invalidates structurally.  The stamp
    is defense in depth against an invalidation path the first law
    missed; it is NOT sufficient alone (an element write carrying an
    old uuid can change visible content without moving the envelope),
    which is why the intake hooks are the law and the stamp the belt.

GC and element-table compaction never invalidate: they preserve visible
state by construction, and entries hold finished bytes, not row ids.

Bounded: LRU over payload bytes (`CONSTDB_READ_CACHE_MB`; 0 disables),
a single entry never exceeds 1/8 of the cap, and the resident bytes are
a `used_memory` source for the overload governor, whose hard-watermark
reclaim drops the whole cache (server/overload.py — it is exactly a
rebuildable warm cache).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import numpy as np

_I64 = np.int64

# per-entry bookkeeping overhead charged on top of the payload bytes
# (dict slots, the stamp tuple, the by-key index entry)
_ENTRY_OVERHEAD = 200


def _noop(*_a) -> None:
    return None


# member-scoped entry kinds: their reply depends on ONE element of the
# key (the args-digest member/field), so an element write invalidates
# only the touched members' entries (invalidate_key_members) — every
# other kind reads the whole key and always drops
_MEMBER_SCOPED = frozenset((b"sismember", b"hget"))


class ReadReplyCache:
    """Bounded (command, key, args) -> stamped reply-bytes map."""

    __slots__ = ("cap_bytes", "bytes", "hits", "misses", "invalidations",
                 "_map", "_by_key")

    def __init__(self, cap_bytes: int = 0) -> None:
        self.cap_bytes = cap_bytes
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        # (name, key, extra) -> [kid, ct, mt, dt, payload]
        self._map: OrderedDict[tuple, list] = OrderedDict()
        self._by_key: dict[bytes, set] = {}

    def configure(self, cap_bytes: int) -> None:
        self.cap_bytes = max(0, cap_bytes)
        if not self.cap_bytes:
            self.clear()
        else:
            self._shrink()

    @property
    def enabled(self) -> bool:
        return self.cap_bytes > 0

    def __len__(self) -> int:
        return len(self._map)

    def used_bytes(self) -> int:
        """Governed residency (overload-governor source)."""
        return self.bytes

    # ----------------------------------------------------------------- ops

    def get(self, name: bytes, key: bytes, extra: bytes,
            ks) -> Optional[bytes]:
        """The cached reply, iff the key's live envelope still matches
        the entry's fill-time stamp (a mismatch drops the entry — some
        write moved the envelope without passing an intake hook we
        instrument, e.g. a lazy expiry raced the EXPIRE invalidation).
        Absent-key entries (kid == -1) verify by the key still being
        absent — exact, since an absent key has one fixed reply per
        command.  Counts a hit or a miss either way.  Delegates to
        `get_batch` so the stamp-verify rule lives in one place."""
        return self.get_batch([(name, key, extra)], ks)[0]

    def get_batch(self, reqs: list, ks) -> list:
        """Batched probe for one planned read run: `reqs` is a list of
        `(name, key, extra)` tuples, the result a payload-or-None list.
        All present entries' stamps verify in ONE vectorized pass over
        the envelope columns (the per-entry scalar reads were the
        measured hot cost of the hit path); mismatched entries drop
        exactly like `get`'s."""
        m = self._map
        ents = [m.get(r) for r in reqs]
        hit_idx = [i for i, e in enumerate(ents) if e is not None]
        out: list = [None] * len(reqs)
        if not hit_idx:
            self.misses += len(reqs)
            return out
        keys = ks.keys
        under_pressure = self.bytes * 2 >= self.cap_bytes
        move = m.move_to_end if under_pressure else _noop
        if len(hit_idx) < 16:
            # below the vectorization floor the fancy-index setup costs
            # more than the scalar verifies it replaces
            hits = 0
            ct, mt, dt, exp = keys.ct, keys.mt, keys.dt, keys.expire
            lookup = ks.key_index.lookup
            for i in hit_idx:
                ent = ents[i]
                kid = ent[0]
                if kid < 0:
                    good = lookup(reqs[i][1]) < 0
                else:
                    good = dt[kid] == ent[3] and not exp[kid] and \
                        (ent[2] < 0 or (ct[kid] == ent[1] and
                                        mt[kid] == ent[2]))
                if good:
                    out[i] = ent[4]
                    move(reqs[i])
                    hits += 1
                else:
                    self._drop(reqs[i])
            self.hits += hits
            self.misses += len(reqs) - hits
            return out
        pos_idx = [i for i in hit_idx if ents[i][0] >= 0]
        neg_idx = [i for i in hit_idx if ents[i][0] < 0]
        ok_by_i: dict = {}
        if pos_idx:
            kid_arr = np.fromiter((ents[i][0] for i in pos_idx),
                                  dtype=_I64, count=len(pos_idx))
            mt_st = np.fromiter((ents[i][2] for i in pos_idx),
                                dtype=_I64, count=len(pos_idx))
            # member-scoped entries (stamp -1) skip the ct/mt checks
            ok = (mt_st < 0) | (
                (keys.ct[kid_arr] ==
                 np.fromiter((ents[i][1] for i in pos_idx), dtype=_I64,
                             count=len(pos_idx))) &
                (keys.mt[kid_arr] == mt_st))
            ok &= (keys.dt[kid_arr] ==
                   np.fromiter((ents[i][3] for i in pos_idx), dtype=_I64,
                               count=len(pos_idx)))
            ok &= keys.expire[kid_arr] == 0
            for x, i in enumerate(pos_idx):
                ok_by_i[i] = bool(ok[x])
        if neg_idx:
            # absent-key entries: one batched index probe proves every
            # key is STILL absent
            found = ks.key_index.lookup_batch(
                [reqs[i][1] for i in neg_idx])
            for x, i in enumerate(neg_idx):
                ok_by_i[i] = found[x] < 0
        hits = 0
        for i in hit_idx:
            if ok_by_i[i]:
                out[i] = ents[i][4]
                move(reqs[i])
                hits += 1
            else:
                self._drop(reqs[i])
        self.hits += hits
        self.misses += len(reqs) - hits
        return out

    def put(self, name: bytes, key: bytes, extra: bytes, kid: int,
            ks, payload: bytes, env=None) -> None:
        """Stamp + store one finished reply.  Expiry-armed keys are
        never cacheable (time-dependent visibility); ABSENT keys are
        (`kid < 0` — their reply is fixed per command until a creation,
        which every intake hook invalidates, and the hit-time verify
        re-proves absence); oversized replies (> cap/8) are skipped
        rather than evicting the whole working set.  `env`: the key's
        already-gathered `(ct, dt, expire)`-era stamp source as
        `(ct, dt)` with expire known 0 — the read planner passes it so
        the fill pays no column re-reads (mt is read here either way)."""
        if not self.enabled:
            return
        if len(payload) + _ENTRY_OVERHEAD > self.cap_bytes >> 3:
            return
        if kid >= 0:
            keys = ks.keys
            # member-scoped kinds (sismember/hget) read ONE element:
            # their stamp skips ct/mt (stored -1), because EVERY element
            # write advances both (updated_at's max rule) while touching
            # only the members it names — which the member-scoped intake
            # hooks already invalidate exactly.  dt/expire still verify:
            # key deletes bump dt (and fully invalidate at intake), and
            # expiry arming must always drop.
            if name in _MEMBER_SCOPED:
                if env is not None:
                    ent = [kid, -1, -1, env[1], payload]
                elif int(keys.expire[kid]) != 0:
                    return  # time-dependent visibility — never cached
                else:
                    ent = [kid, -1, -1, int(keys.dt[kid]), payload]
            elif env is not None:
                ent = [kid, env[0], int(keys.mt[kid]), env[1], payload]
            else:
                if int(keys.expire[kid]) != 0:
                    return  # time-dependent visibility — never cached
                ent = [kid, int(keys.ct[kid]), int(keys.mt[kid]),
                       int(keys.dt[kid]), payload]
        else:
            ent = [-1, 0, 0, 0, payload]
        k = (name, key, extra)
        if k in self._map:
            self._drop(k)
        self._map[k] = ent
        self._by_key.setdefault(key, set()).add(k)
        self.bytes += len(payload) + _ENTRY_OVERHEAD
        self._shrink()

    # -------------------------------------------------------- invalidation

    def invalidate_key(self, key: bytes) -> None:
        """Drop every entry for `key` (one mutation intake observed it)."""
        ks = self._by_key.pop(key, None)
        if not ks:
            return
        self.invalidations += len(ks)
        for k in ks:
            ent = self._map.pop(k, None)
            if ent is not None:
                self.bytes -= len(ent[4]) + _ENTRY_OVERHEAD

    def invalidate_key_members(self, key: bytes, members) -> None:
        """Element-write intake (sadd/srem/hset/hdel): the write touches
        exactly `members` of `key`, so member-scoped entries (sismember/
        hget — their reply reads ONE element) survive unless their
        member was touched; every whole-key kind (scans, counts, get,
        envelope-dependent replies) drops.  This is what lets a hot
        key's probe working set survive writes to its other members.
        Falls back to the full drop when `members` is None (shape the
        caller could not scope)."""
        ks = self._by_key.get(key)
        if not ks:
            return
        if members is None:
            self.invalidate_key(key)
            return
        memberset = members if type(members) is set else set(members)
        dead = [k for k in ks
                if k[0] not in _MEMBER_SCOPED or k[2] in memberset]
        self.invalidations += len(dead)
        m = self._map
        for k in dead:
            ent = m.pop(k, None)
            if ent is not None:
                self.bytes -= len(ent[4]) + _ENTRY_OVERHEAD
            ks.discard(k)
        if not ks:
            del self._by_key[key]

    def invalidate_keys(self, keys) -> None:
        """Bulk intake (a merged ColumnarBatch's key lists).  When the
        batch names more keys than the cache holds entries, clearing
        outright is cheaper than probing each key (snapshot ingest at
        north-star scale must not pay O(rows) dict probes into an
        empty cache)."""
        if not self._map:
            return
        try:
            n = len(keys)
        except TypeError:
            keys = list(keys)
            n = len(keys)
        if n >= len(self._map):
            self.invalidations += len(self._map)
            self._map.clear()
            self._by_key.clear()
            self.bytes = 0
            return
        by_key = self._by_key
        for key in keys:
            if key in by_key:
                self.invalidate_key(key)

    def clear(self) -> None:
        """State wipe / hard-watermark reclaim: drop everything (counted
        as invalidations — the gauges must explain a hit-rate cliff)."""
        self.invalidations += len(self._map)
        self._map.clear()
        self._by_key.clear()
        self.bytes = 0

    # ------------------------------------------------------------ internal

    def _drop(self, k: tuple) -> None:
        ent = self._map.pop(k, None)
        if ent is None:
            return
        self.bytes -= len(ent[4]) + _ENTRY_OVERHEAD
        s = self._by_key.get(k[1])
        if s is not None:
            s.discard(k)
            if not s:
                del self._by_key[k[1]]

    def _shrink(self) -> None:
        while self.bytes > self.cap_bytes and self._map:
            self._drop(next(iter(self._map)))
