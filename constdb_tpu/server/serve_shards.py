"""Shard-per-core serving plane: route the client path across host cores.

PR 2 built the hash-sharded keyspace and a forkserver worker pool as a
snapshot-ingest accelerator; this module turns that machinery into the
SERVING architecture.  With `CONSTDB_SERVE_SHARDS=N` (N > 1) a node runs
N serve workers (parallel/serve_pool.py), each owning one keyspace shard
+ merge engine + repl-log segment, and the event loop becomes a ROUTER:

  * **key-hash routing** — every first-key-confined command (all data
    commands; the KEY-CONFINED lint rule pins the convention) executes
    entirely inside the worker owning `crc32(key) % N`, through the same
    ServeCoalescer machinery PR 5 built.  Pipelined chunks ship as one
    sub-chunk per shard, so the per-command pipe cost amortizes exactly
    like the per-command merge cost did.
  * **central clock** — the parent mints EVERY uuid at route time with
    the same `tick(is_write)` discipline `commands.execute` applies, in
    request order.  The uuid stream is therefore byte-identical to the
    single-loop path's, which is what makes the multi-shard differential
    suite able to demand byte-identical replies, exports, and merged
    repl logs (tests/test_serve_shards.py).
  * **ordered barrier plane** — cross-shard commands (admin/CTRL,
    membership, INFO, SYNC upgrades) quiesce the chunk's outstanding
    sub-chunks, then execute on the parent loop, exactly mirroring the
    intra-connection barrier semantics PR 5 pinned.
  * **merge-sorted peer stream** — each worker's locally-executed writes
    mirror into that shard's parent-side repl-log segment as acks land;
    `MergedReplLog` (server/repl_log.py) merge-sorts the segments back
    into one HLC-ordered stream, gated below the FLOOR (the smallest
    minted-but-unlanded write uuid) so emission order is strictly
    increasing.  Watermarks, REPLACK beacons, and the partial-resync
    decision are unchanged on the wire — an unmodified peer replicates
    from a sharded node without knowing it is sharded.

`CONSTDB_SERVE_SHARDS=1` (the default) never constructs this plane —
the node runs the exact PR 5 single-loop path, byte for byte.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from ..errors import CstError, ReplicateCommandsLost
from ..resp.codec import encode_into
from ..resp.message import Arr, Bulk, NoReply, as_bytes, as_int
from ..store.sharded_keyspace import MAX_SHARDS, shard_of
from .commands import (CMD_CTRL, CMD_REPL_ONLY, COMMANDS,
                       STATE_FREE_BARRIERS, shard_routable)
from .events import EVENT_DELETED, EVENT_PULL_LANDED, EVENT_REPLICATED
from .repl_log import MergedReplLog

log = logging.getLogger(__name__)

_STAT_GAUGES = (("msgs", "msgs"), ("flushes", "flushes"),
                ("barriers", "barriers"), ("keys", "keys"),
                ("used_bytes", "used_bytes"),
                ("reads", "reads"), ("read_flushes", "read_flushes"),
                ("cache_hits", "cache_hits"),
                ("cache_misses", "cache_misses"),
                ("cache_bytes", "cache_bytes"))


class _Sub:
    """One shard's slice of the pre-barrier run being classified."""

    __slots__ = ("msgs", "uuids", "idxs", "token")

    def __init__(self) -> None:
        self.msgs: list = []
        self.uuids: list = []
        self.idxs: list = []
        self.token: Optional[int] = None


class ServeShardPlane:
    """Parent-side router + authority for a shard-per-core serving node
    (see module docstring)."""

    def __init__(self, app, n_shards: int, engine_spec: str = "cpu"):
        if not 2 <= n_shards <= MAX_SHARDS:
            raise ValueError(f"serve_shards must be in [2, {MAX_SHARDS}]")
        self.app = app
        self.node = app.node
        self.n_shards = n_shards
        self.engine_spec = engine_spec
        self.pool = None
        self.merged = MergedReplLog(n_shards,
                                    cap_bytes=self.node.repl_log.cap)
        self.merged.floor = self._floor
        self.merged.pending_high = self._pending_high
        # minted-but-unlanded write uuid windows: token -> [wmin, wmax].
        # Opened at MINT time (before any await can let the push loop
        # emit a newer entry), closed by the serve-ack callback AFTER
        # the worker's log entries mirrored into the segment.
        self._inflight: dict[int, list] = {}
        self._next_token = 0
        self._last_stats = [dict() for _ in range(n_shards)]
        # Serve-ack ORDERING: worker futures complete FIFO per shard,
        # but their handlers can run out of order ACROSS connections —
        # one connection's inline quiesce ack for a later window vs
        # another connection's still-queued done-callback for an
        # earlier one.  Mirroring the later window's entries into the
        # segment first would make push_many reject the earlier
        # window (uuid regression) and silently LOSE its acked writes
        # from the repl stream and the AOF.  Every dispatched
        # sub-chunk takes a per-shard ticket; handlers drain tickets
        # strictly in ticket order (worker FIFO means a later future
        # being done implies every earlier one is).
        self._ack_pend: list[dict] = [dict() for _ in range(n_shards)]
        self._ack_seq = [0] * n_shards
        self._ack_next = [0] * n_shards

    # ----------------------------------------------------------- lifecycle

    async def start(self) -> None:
        from ..parallel.serve_pool import ServeShardPool
        node = self.node
        gov = node.governor
        self.pool = ServeShardPool(self.n_shards,
                                   engine_spec=self.engine_spec,
                                   node_id=node.node_id, alias=node.alias,
                                   serve_batch=self.app.serve_batch,
                                   # each worker governs its slice of
                                   # the node cap (serve_pool worker
                                   # main; 0 stays unlimited)
                                   maxmemory=gov.maxmemory
                                   // self.n_shards,
                                   maxmemory_soft_pct=gov.soft_pct)
        node.serve_plane = self
        node.repl_log = self.merged
        x = node.stats.extra
        x["serve_shards"] = self.n_shards
        x["serve_shard_map"] = f"crc32(key)%{self.n_shards}"
        x.setdefault("serve_xshard_barriers", 0)
        log.info("serve plane up: %d shard workers (engine=%s)",
                 self.n_shards, self.engine_spec)

    async def close(self) -> None:
        if self.pool is not None:
            await self.pool.close()

    # ------------------------------------------------------- floor windows

    def _floor(self) -> Optional[int]:
        if not self._inflight:
            return None
        return min(w[0] for w in self._inflight.values())

    def _pending_high(self) -> int:
        if not self._inflight:
            return 0
        return max(w[1] for w in self._inflight.values())

    def _open_window(self, uuid: int) -> int:
        tok = self._next_token
        self._next_token += 1
        self._inflight[tok] = [uuid, uuid]
        return tok

    # ------------------------------------------------------------- routing

    async def run_chunk(self, msgs: list, out: bytearray,
                        client=None) -> None:
        """Plan, route, and execute one drained chunk of client
        messages, appending every reply to `out` in request order.

        `client` is the connection's ClientConn (server/tracking.py).
        The PARENT owns every tracked subscription on a sharded node —
        invalidation streams fold through this routing plane: a routed
        write invalidates at route time (before the worker executes it,
        so invalidate-before-visible holds), a routed read feeds
        default-mode note_read, and barrier commands carry the client
        into the parent-side execute (HELLO / CLIENT TRACKING work
        unchanged)."""
        node = self.node
        tracking = node.tracking
        n = len(msgs)
        if not n:
            return
        replies: list = [b""] * n
        subs: dict[int, _Sub] = {}
        futs: list = []       # (future, idxs) of dispatched sub-chunks
        opened: set = set()   # window tokens opened by this chunk
        dispatched: set = set()
        lone = n == 1

        def dispatch() -> None:
            # synchronous by design: no suspension point may separate
            # uuid minting from the pipe write (parallel/serve_pool.py)
            for shard, sub in subs.items():
                payload = bytearray()
                for m in sub.msgs:
                    encode_into(payload, m)
                fut = self.pool.submit(
                    shard, ("serve", bytes(payload), sub.uuids,
                            len(sub.msgs)))
                if sub.token is not None:
                    dispatched.add(sub.token)
                seq = self._ack_seq[shard]
                self._ack_seq[shard] = seq + 1
                self._ack_pend[shard][seq] = (sub.token, fut)
                fut.add_done_callback(
                    lambda f, s=shard: self._on_serve_ack(s))
                futs.append((fut, sub.idxs, shard, sub.token))
            subs.clear()

        async def quiesce() -> None:
            dispatch()
            for fut, idxs, shard, token in futs:
                res = await fut
                # run the ack bookkeeping NOW, not "soon": a future that
                # resolved while this loop was awaiting an EARLIER one
                # returns from its await without yielding, with its
                # done-callback still queued behind this task's wakeup —
                # a barrier executing right after quiesce would then
                # read the merged repl_log MISSING entries whose writes
                # already replied OK (found by the overload round's
                # stress runs: REPLLOG UUIDS intermittently saw one
                # shard's sub-chunk absent).  The ticket drain is
                # idempotent, so the still-queued callback is a no-op —
                # and remains the mirror-of-record when a client
                # disconnect cancels this coroutine mid-quiesce.
                self._on_serve_ack(shard)
                sout, spans = res[0], res[1]
                prev = 0
                for j, idx in enumerate(idxs):
                    replies[idx] = sout[prev:spans[j]]
                    prev = spans[j]
            futs.clear()

        try:
            for i, msg in enumerate(msgs):
                routed = False
                items = msg.items if type(msg) is Arr else None
                cmd = None
                if items:
                    head = items[0]
                    name = head.val if type(head) is Bulk else None
                    if name is not None:
                        cmd = COMMANDS.get(name) or COMMANDS.get(name.lower())
                if cmd is not None and shard_routable(cmd) and \
                        not (cmd.flags & CMD_REPL_ONLY) and len(items) > 1:
                    try:
                        key = as_bytes(items[1])
                    except Exception:
                        key = None  # execute() raises the exact op error
                    if key is not None:
                        if tracking is not None and tracking.active:
                            if cmd.is_write:
                                tracking.invalidate_key(key)
                            elif client is not None and \
                                    client.tracking == 1:
                                tracking.note_read(client, key)
                        shard = shard_of(key, self.n_shards)
                        uuid = node.hlc.tick(cmd.is_write)
                        sub = subs.get(shard)
                        if sub is None:
                            sub = subs[shard] = _Sub()
                        if cmd.is_write:
                            if sub.token is None:
                                sub.token = self._open_window(uuid)
                                opened.add(sub.token)
                            else:
                                self._inflight[sub.token][1] = uuid
                        sub.msgs.append(msg)
                        sub.uuids.append(uuid)
                        sub.idxs.append(i)
                        routed = True
                if routed:
                    continue
                # ordered barrier plane: land this chunk's outstanding
                # routed commands, then execute on the parent loop
                had_outstanding = bool(subs) or bool(futs)
                await quiesce()
                if had_outstanding:
                    node.stats.extra["serve_xshard_barriers"] = \
                        node.stats.extra.get("serve_xshard_barriers", 0) + 1
                reply = node.execute(msg, client=client)
                if not lone:
                    node.stats.serve_barriers += 1
                if not isinstance(reply, NoReply):
                    buf = bytearray()
                    encode_into(buf, reply)
                    replies[i] = bytes(buf)
                if cmd is not None and cmd.flags & CMD_CTRL:
                    # CTRL can change the node identity the workers
                    # stamp into writes (NODE ID) — resync them
                    await self.pool.call_all("ident", node.node_id,
                                             node.alias)
            await quiesce()
        finally:
            for tok in opened - dispatched:
                self._inflight.pop(tok, None)
        for r in replies:
            out += r

    def _on_serve_ack(self, shard: int) -> None:
        """Reply-order ack bookkeeping (FIFO per shard): drain this
        shard's ack tickets strictly in dispatch order, stopping at the
        first unresolved future.  Called both inline from quiesce()
        for already-resolved futures (see the race note there) and
        from every done-callback; each ticket is processed exactly
        once, and a ticket is never processed before every earlier
        ticket of its shard — the ordering push_many and the AOF
        segment mirror both require."""
        pend = self._ack_pend[shard]
        while True:
            entry = pend.get(self._ack_next[shard])
            if entry is None or not entry[1].done():
                return
            del pend[self._ack_next[shard]]
            self._ack_next[shard] += 1
            self._ack_one(shard, entry[0], entry[1])

    def _ack_one(self, shard: int, token: Optional[int], fut) -> None:
        """Land one resolved sub-chunk: mirror the worker's log entries
        into this shard's segment (and the AOF), then release the floor
        window, then wake the pushers — that order is what keeps the
        merged stream strictly increasing."""
        if fut.cancelled() or fut.exception() is not None:
            # the worker failed mid-chunk: its entries may be missing,
            # so the window stays HELD — the peer stream stalls on this
            # shard instead of silently skipping ops (the awaiting
            # connection sees the raised error)
            log.error("serve worker %d chunk failed; holding repl floor: "
                      "%s", shard,
                      None if fut.cancelled() else fut.exception())
            return
        _out, _spans, entries, deleted, stats = fut.result()
        node = self.node
        if entries:
            self.merged.segments[shard].push_many(entries)
            if node.oplog is not None:
                # the shard's durable segment mirrors in the same ack
                # order as its repl-log segment (persist/oplog.py:
                # per-shard segment files, merged by HLC at replay)
                for uuid, name, args in entries:
                    node.oplog.append_local(uuid, name, args, seg=shard)
        if token is not None:
            self._inflight.pop(token, None)
        if entries:
            node.events.trigger(EVENT_REPLICATED, entries[-1][0])
        if deleted:
            node.events.trigger(EVENT_DELETED)
        self._fold_stats(shard, stats)

    def _fold_stats(self, shard: int, stats: dict) -> None:
        node = self.node
        last = self._last_stats[shard]
        st = node.stats
        st.cmds_processed += stats["cmds"] - last.get("cmds", 0)
        st.cmds_replicated += stats["repl"] - last.get("repl", 0)
        st.serve_msgs_coalesced += stats["msgs"] - last.get("msgs", 0)
        st.serve_flushes += stats["flushes"] - last.get("flushes", 0)
        st.serve_barriers += stats["barriers"] - last.get("barriers", 0)
        # read-plane worker deltas fold into the node totals: the stat
        # counters directly, the cache counters into the parent's cache
        # object (unused for serving in sharded mode, so its own counts
        # stay zero and the fold IS the node total)
        st.serve_reads_coalesced += stats["reads"] - last.get("reads", 0)
        st.serve_read_flushes += \
            stats["read_flushes"] - last.get("read_flushes", 0)
        rc = node.read_cache
        rc.hits += stats["cache_hits"] - last.get("cache_hits", 0)
        rc.misses += stats["cache_misses"] - last.get("cache_misses", 0)
        rc.invalidations += stats["cache_inv"] - last.get("cache_inv", 0)
        st.repl_apply_barriers += \
            stats["apply_barriers"] - last.get("apply_barriers", 0)
        st.oom_shed_writes += stats["oom_shed"] - last.get("oom_shed", 0)
        if stats.get("lat"):
            st.serve_lat.extend(stats["lat"])
        self._last_stats[shard] = stats
        x = st.extra
        for ext, key in _STAT_GAUGES:
            x[f"serve_shard{shard}_{ext}"] = stats[key]

    # -------------------------------------------------- replication (pull)

    def make_applier(self, meta, max_frames=None, max_latency=None,
                     now=time.monotonic) -> "ShardApplier":
        return ShardApplier(self, meta, max_frames=max_frames,
                            max_latency=max_latency, now=now)

    # -------------------------------------------------------- bulk / reads

    async def ingest_batches(self, batches) -> int:
        """Fan decoded snapshot batches out to the shard workers by key
        hash (the receive side of a full sync).  Awaits per batch, so
        the loop stays live between groups; returns rows applied."""
        from ..persist.snapshot import _encode_batch
        from ..store.sharded_keyspace import extract_shard, shard_ids
        applied = 0
        x = self.node.stats.extra
        tracking = self.node.tracking
        try:
            for b in batches:
                if tracking is not None and tracking.active:
                    # bulk intake (full/delta sync) mutates worker state
                    # without touching the parent command path — the
                    # tracked-invalidation fold happens here, pre-merge
                    tracking.invalidate_keys(b.keys)
                    if b.del_keys:
                        tracking.invalidate_keys(b.del_keys)
                sids = shard_ids(b.keys, self.n_shards)
                dsids = shard_ids(b.del_keys, self.n_shards) \
                    if b.del_keys else None
                futs = []
                for s in range(self.n_shards):
                    sub = extract_shard(b, sids, dsids, s)
                    if sub.n_rows or sub.del_keys:
                        payload = bytes(_encode_batch(sub))
                        futs.append((s, self.pool.submit(
                            s, ("merge", payload))))
                for s, f in futs:
                    rows, nkeys = await f
                    applied += rows
                    x[f"serve_shard{s}_keys"] = nkeys
        finally:
            # even a PARTIAL ingest invalidates the shared full-sync
            # dump: bulk-merged rows bypass the repl_log, so a cached
            # dump plus a log tail would silently omit them (the plain
            # path invalidates per merge_batches call)
            self.node._dump_stale()
        return applied

    async def export_batches(self) -> list:
        """Whole-state columnar export of every shard (quiesced +
        flushed) — the full-sync dump feed (persist/share.py)."""
        from ..persist.snapshot import _decode_batch
        payloads = await self.pool.call_all("export")
        return [_decode_batch(p) for p in payloads]

    async def key_count(self) -> int:
        """Live key total across the workers (delta-sync leaf sizing,
        replica/link.py _send_delta).  Asked of the workers directly:
        the `serve_shard<i>_keys` stat gauges only update on serve-chunk
        acks and catch-up ingests, so a node whose state arrived purely
        via the replication stream would size its digest from zero and
        collapse the leaf granularity."""
        return sum(await self.pool.call_all("n_keys"))

    async def state_digest(self, fanout: int, leaves: int):
        """The plane's (fanout, leaves) anti-entropy digest matrix
        (replica/link.py delta sync): each worker folds ITS disjoint key
        set over the negotiated crc32 partition and the parent sums the
        matrices — the fold is an unordered sum, so plane-wide = Σ
        per-worker whatever the worker count (store/digest.py)."""
        from ..store.digest import sum_matrices
        mats = await self.pool.call_all("digest", fanout, leaves)
        return sum_matrices(mats, fanout, leaves).astype("<u8")

    async def export_bucket_payloads(self, fanout: int, leaves: int,
                                     mask, chunk_keys: int = 1 << 16
                                     ) -> list:
        """Encoded BATCH-section chunks of the masked digest buckets'
        state, from every worker (the delta-sync stream's payload —
        written as-is via SnapshotWriter.write_chunk_raw, no parent-side
        decode/re-encode)."""
        import numpy as np
        parts = await self.pool.call_all(
            "digest_export", fanout, leaves,
            np.asarray(mask, dtype=bool).tobytes(), chunk_keys)
        return [p for chunks in parts for p in chunks]

    async def canonical(self, keys=None) -> dict:
        if keys is None:
            parts = await self.pool.call_all("canonical", None)
        else:
            per: list[list] = [[] for _ in range(self.n_shards)]
            for k in keys:
                per[shard_of(k, self.n_shards)].append(k)
            futs = [self.pool.submit(s, ("canonical", per[s]))
                    for s in range(self.n_shards) if per[s]]
            parts = list(await asyncio.gather(*futs))
        out: dict = {}
        for p in parts:
            out.update(p)
        return out

    async def state_bytes_per_shard(self) -> list:
        return await self.pool.call_all("state_bytes")

    async def gc(self, horizon: int) -> int:
        freed = sum(await self.pool.call_all("gc", horizon))
        self.node.stats.gc_freed += freed
        return freed

    async def reset_for_resync(self, keep_link=None) -> None:
        """The plane twin of Node.reset_for_full_resync: quiesce, wipe
        every shard worker, fence fresh segments at the pre-wipe
        watermark, and kick every other live peer connection."""
        node = self.node
        tr = node.tracking
        if tr is not None and tr.active:
            tr.flush_all()  # the wiped state invalidates EVERY near-cache
        await self.pool.barrier()
        fence = max(self.merged.last_uuid, node.hlc.current)
        await self.pool.call_all("reset")
        merged = MergedReplLog(self.n_shards, cap_bytes=self.merged.cap)
        merged.floor = self._floor
        merged.pending_high = self._pending_high
        merged.last_uuid = fence
        merged.evicted_up_to = fence
        self.merged = merged
        node.repl_log = merged
        self._inflight.clear()
        if node.oplog is not None:
            # same rule as Node.reset_for_full_resync: the log describes
            # discarded state — truncate + fence + reinstall the floor
            # on the fresh merged log (persist/oplog.py on_wipe)
            node.oplog.on_wipe(fence)
        node._kick_peers_after_wipe(keep_link)


class ShardApplier:
    """Peer-stream applier for a sharded node: intake (dup-skip / gap /
    cursor) stays on the parent loop, frames route to the worker owning
    their key and apply there on the exact per-key op path — cross-shard
    parallelism replaces in-shard coalescing.  Watermark discipline is
    identical to replica/coalesce.py: `meta.uuid_he_sent` advances only
    after the covering worker acks land, beacons are stashed while
    frames are pending, and membership frames apply in place (they never
    touch the keyspace)."""

    needs_flush_async = True

    __slots__ = ("plane", "node", "meta", "max_frames", "max_latency",
                 "_now", "cursor", "_epoch", "_bufs", "_counts", "_frames",
                 "_first_ts", "_pending_beacon")

    def __init__(self, plane: ServeShardPlane, meta, max_frames=None,
                 max_latency=None, now=time.monotonic) -> None:
        from ..conf import env_float, env_int
        self.plane = plane
        self.node = plane.node
        self.meta = meta
        self.max_frames = env_int("CONSTDB_APPLY_BATCH", 512) \
            if max_frames is None else max_frames
        self.max_latency = (env_float("CONSTDB_APPLY_LATENCY_MS", 5.0)
                            / 1000.0) if max_latency is None else max_latency
        self._now = now
        self.cursor = meta.uuid_he_sent
        self._epoch = plane.node.reset_epoch
        self._bufs = [bytearray() for _ in range(plane.n_shards)]
        self._counts = [0] * plane.n_shards
        self._frames = 0
        self._first_ts = 0.0
        self._pending_beacon = 0

    @property
    def pending(self) -> int:
        return self._frames

    @property
    def pending_bytes(self) -> int:
        """Buffered-but-unlanded frame bytes (overload accounting —
        the pull loop registers a governor source reading this)."""
        return sum(map(len, self._bufs))

    async def aapply(self, items: list) -> None:
        uuid = as_int(items[3])
        if uuid <= self.cursor:
            return  # duplicate (reconnect overlap)
        if as_int(items[2]) > self.cursor:
            await self.aflush()
            raise ReplicateCommandsLost(
                f"{self.meta.addr}: gap {self.cursor} -> "
                f"{as_int(items[2])}")
        name = as_bytes(items[4])
        cmd = COMMANDS.get(name) or COMMANDS.get(name.lower())
        if cmd is None or not shard_routable(cmd) or len(items) < 6:
            # membership applies in place (never touches the keyspace);
            # anything else unroutable lands what we have first, then
            # takes the exact per-key path on the parent (raising the
            # exact op error for unknown/malformed frames)
            if self._frames and name not in STATE_FREE_BARRIERS:
                await self.aflush()
            node = self.node
            node.stats.repl_apply_barriers += 1
            node.apply_replicated(name, items[5:], as_int(items[1]), uuid)
            if node.oplog is not None:
                node.oplog.append_frame(as_int(items[1]), uuid, name,
                                        list(items[5:]),
                                        seg=self.plane.n_shards)
            self.cursor = uuid
            if not self._frames:
                self._advance(uuid)
            return
        key = as_bytes(items[5])
        tr = self.node.tracking
        if tr is not None and tr.active:
            # replicated write folding into a worker: invalidate on the
            # parent BEFORE the frame routes (the sharded twin of
            # apply_replicated's pre-land invalidation)
            tr.invalidate_key(key)
        shard = shard_of(key, self.plane.n_shards)
        if not self._frames:
            self._first_ts = self._now()
        encode_into(self._bufs[shard], Arr(items))
        if self.node.oplog is not None:
            self.node.oplog.append_frame(as_int(items[1]), uuid, name,
                                         list(items[5:]), seg=shard)
        self._counts[shard] += 1
        f = self._frames + 1
        self._frames = f
        self.cursor = uuid
        if f >= self.max_frames or \
                (not f & 31 and
                 self._now() - self._first_ts >= self.max_latency):
            await self.aflush()

    async def aabatch(self, items: list) -> None:
        """REPLBATCH on a sharded receiver is a protocol violation: this
        node never advertises CAP_BATCH_STREAM (replica/link.py my_caps)
        because frames apply per-key inside the worker owning their
        shard — there is no single keyspace for a decoded batch to merge
        into.  A peer that sends one anyway loses the connection loudly
        and redelivers per-frame from the landed watermark."""
        raise CstError(f"{self.meta.addr}: replbatch frame on a sharded "
                       "receiver (capability was never advertised)")

    def observe_beacon(self, beacon: int) -> None:
        if self._frames:
            if beacon > max(self.cursor, self._pending_beacon):
                self._pending_beacon = beacon
                self.node.hlc.observe(beacon)
        elif beacon > self.meta.uuid_he_sent:
            self.meta.uuid_he_sent = beacon
            if beacon > self.cursor:
                self.cursor = beacon
            self.node.hlc.observe(beacon)

    def resync(self) -> None:
        self.cursor = self.meta.uuid_he_sent
        self._pending_beacon = 0
        self._epoch = self.node.reset_epoch

    async def aflush(self) -> None:
        frames, self._frames = self._frames, 0
        if not frames:
            return
        bufs = self._bufs
        counts = self._counts
        self._bufs = [bytearray() for _ in range(self.plane.n_shards)]
        self._counts = [0] * self.plane.n_shards
        node = self.node
        if node.reset_epoch != self._epoch:
            # a state wipe landed between intake and flush: these frames
            # describe pre-wipe state — drop them (replica/coalesce.py)
            self._pending_beacon = 0
            return
        pool = self.plane.pool
        futs = []
        for s in range(self.plane.n_shards):
            if counts[s]:
                futs.append((s, pool.submit(
                    s, ("apply", bytes(bufs[s]), counts[s]))))
        for s, f in futs:
            entries, deleted, stats = await f
            if entries:  # leftover tap from an earlier worker error
                self.plane.merged.segments[s].push_many(entries)
                if node.oplog is not None:
                    for uuid, name, args in entries:
                        node.oplog.append_local(uuid, name, args, seg=s)
            if deleted:
                node.events.trigger(EVENT_DELETED)
            self.plane._fold_stats(s, stats)
        node.hlc.observe(self.cursor)
        self._advance(self.cursor, wake=frames >= 2)

    def _advance(self, uuid: int, wake: bool = False) -> None:
        # `wake` discipline mirrors replica/coalesce.py _advance: only a
        # genuine multi-frame land wakes push loops to REPLACK it now;
        # trickle lands keep their heartbeat-cadence acks
        beacon, self._pending_beacon = self._pending_beacon, 0
        w = max(uuid, beacon)
        if w > self.meta.uuid_he_sent:
            self.meta.uuid_he_sent = w
            if wake:
                self.node.events.trigger(EVENT_PULL_LANDED)
        if beacon > self.cursor:
            self.cursor = beacon
