"""Replication log: byte-capped ring of locally-executed write commands.

Capability parity with the reference's repl_log (reference
src/server.rs:35-38 ring + cap, 270-288 push/evict, 290-379 queries with
binary search by uuid).  Entries are only ever appended with strictly
increasing uuids (the HLC guarantees this for local writes), so lookups are
binary searches over a deque of sorted uuids.

The ring additionally tracks `evicted_up_to` — the uuid of the newest entry
ever evicted — so partial-resync eligibility is exact: a peer resuming from
uuid `u` can be served incrementally iff `u >= evicted_up_to` (the reference
infers this more loosely in push.rs:95-110).
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from collections import deque
from itertools import islice
from typing import Optional

from ..resp.message import Arr, Bulk, Msg, msg_size


class ReplEntry:
    __slots__ = ("uuid", "prev_uuid", "name", "args", "size")

    def __init__(self, uuid: int, prev_uuid: int, name: bytes, args: list, size: int):
        self.uuid = uuid
        self.prev_uuid = prev_uuid
        self.name = name
        self.args = args
        self.size = size


class ReplLog:
    # parity: reference src/server.rs:81 (size-based cap, 1_024_000 bytes)
    DEFAULT_CAP = 1_024_000

    def __init__(self, cap_bytes: int = DEFAULT_CAP):
        self.cap = cap_bytes
        self._entries: deque[ReplEntry] = deque()
        self._uuids: deque[int] = deque()  # parallel, for bisect
        self._bytes = 0
        self.evicted_up_to = 0  # uuid of the newest evicted entry (0 = none)
        self.last_uuid = 0      # newest uuid ever pushed (survives eviction)
        # observer: called with (uuid, name, args) as each entry lands —
        # the chaos oracle's op journal taps the origin stream here
        # (constdb_tpu/chaos/oracle.py); the ring's eviction makes the
        # log itself useless as a post-hoc record.  None = no observer.
        self.on_append = None
        # emission floor: None, or a callable returning the smallest
        # uuid the push stream may NOT emit yet (entries with
        # uuid >= floor() are invisible to next_after/run_after — the
        # MergedReplLog floor discipline, here for the plain ring).
        # The durable op log installs its fsync horizon here
        # (persist/oplog.py: emit-only-durable law), so a peer can
        # never hold an op a torn tail could still lose.  `last_uuid`
        # stays the true newest on purpose: the drained-beacon check
        # (cursor >= last_uuid) must keep failing below the floor, or a
        # REPLACK beacon would let peers skip the gated window.
        self.floor = None

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def total_bytes(self) -> int:
        return self._bytes

    @property
    def first_uuid(self) -> int:
        return self._uuids[0] if self._uuids else 0

    def push(self, uuid: int, name: bytes, args: list) -> None:
        if uuid <= self.last_uuid:
            raise ValueError(f"repl_log uuids must be increasing: {uuid} <= {self.last_uuid}")
        # args are almost always Bulk; avoid the recursive msg_size call on
        # the op hot path
        size = len(name)
        for a in args:
            v = getattr(a, "val", None)
            size += len(v) if type(v) is bytes else msg_size(a)
        self._entries.append(ReplEntry(uuid, self.last_uuid, name, args, size))
        self._uuids.append(uuid)
        self._bytes += size
        self.last_uuid = uuid
        if self.on_append is not None:
            self.on_append(uuid, name, args)
        while self._bytes > self.cap and len(self._entries) > 1:
            ev = self._entries.popleft()
            self._uuids.popleft()
            self._bytes -= ev.size
            self.evicted_up_to = ev.uuid

    def push_many(self, cmds: list) -> None:
        """Append a planned run of `(uuid, name, args)` tuples in one pass
        (the serve coalescer's flush — server/serve.py).  Semantically
        identical to looping `push` (pinned by tests/test_serve_coalesce),
        but the ring makes ONE eviction sweep at the end instead of one
        per entry, and the hot-loop attribute churn collapses to locals.
        Uuids must be strictly increasing, like every push."""
        if not cmds:
            return
        entries = self._entries
        uuids = self._uuids
        prev = self.last_uuid
        added = 0
        for uuid, name, args in cmds:
            if uuid <= prev:
                raise ValueError(
                    f"repl_log uuids must be increasing: {uuid} <= {prev}")
            size = len(name)
            for a in args:
                # Bulk is ~every argument; dodge the getattr probe
                if type(a) is Bulk:
                    size += len(a.val)
                else:
                    v = getattr(a, "val", None)
                    size += len(v) if type(v) is bytes else msg_size(a)
            entries.append(ReplEntry(uuid, prev, name, args, size))
            uuids.append(uuid)
            added += size
            prev = uuid
        self._bytes += added
        self.last_uuid = prev
        if self.on_append is not None:
            for uuid, name, args in cmds:
                self.on_append(uuid, name, args)
        while self._bytes > self.cap and len(entries) > 1:
            ev = entries.popleft()
            uuids.popleft()
            self._bytes -= ev.size
            self.evicted_up_to = ev.uuid

    def can_resume_from(self, uuid: int) -> bool:
        """Is an incremental stream starting after `uuid` gap-free?
        (partial vs full sync decision — reference push.rs:95-110)."""
        return uuid >= self.evicted_up_to

    def next_after(self, uuid: int) -> Optional[ReplEntry]:
        """The oldest VISIBLE entry with uuid > `uuid` (the next frame
        to push; entries at/above the emission floor are invisible)."""
        i = bisect_right(self._uuids, uuid)
        if i >= len(self._entries):
            return None
        e = self._entries[i]
        if self.floor is not None:
            f = self.floor()
            if f is not None and e.uuid >= f:
                return None
        return e

    def run_after(self, uuid: int, max_n: int,
                  max_bytes: Optional[int] = None) -> list:
        """The RUN of up to `max_n` consecutive entries after `uuid` —
        the batch wire protocol's drain unit (replica/link.py push
        loop).  Equivalent to `max_n` chained `next_after` calls, in one
        O(i + max_n) slice instead of `max_n` bisects; entries in a run
        are gap-free by construction (the ring only evicts from the
        left, and this snapshot is taken synchronously).  `max_bytes`
        additionally cuts the run once the cumulative entry sizes pass
        it (always keeping at least one entry) so a backlog of huge
        values cannot balloon one wire frame — the transport
        backpressure bound the per-frame path got from its 64-frame
        drain cadence."""
        entries = self._entries
        n = len(entries)
        i = bisect_right(self._uuids, uuid)
        if i >= n:
            return []
        # rotate instead of islice-from-zero: a steady-state cursor sits
        # at the TAIL of the ring, where islice would walk the whole
        # deque per call; rotation costs O(min(i, n - i)) — cheap at
        # both ends, where every real cursor lives
        entries.rotate(-i)
        # cap at n - i: the rotation parks the first i entries at the
        # BACK, and an uncapped islice would wrap onto them
        run = list(islice(entries, 0, min(max_n, n - i)))
        entries.rotate(i)
        if self.floor is not None:
            f = self.floor()
            if f is not None:
                for k, e in enumerate(run):
                    if e.uuid >= f:
                        del run[k:]
                        break
        if max_bytes is not None:
            total = 0
            for k, e in enumerate(run):
                total += e.size
                if total > max_bytes and k:
                    del run[k:]
                    break
        return run

    def at(self, uuid: int) -> Optional[ReplEntry]:
        """Exact-uuid lookup (REPLLOG AT — reference server.rs:318-350)."""
        i = bisect_left(self._uuids, uuid)
        if i < len(self._uuids) and self._uuids[i] == uuid:
            return self._entries[i]
        return None

    def uuids(self) -> list[int]:
        return list(self._uuids)

    def entry_as_msg(self, e: ReplEntry) -> Msg:
        """The stored command as a RESP array (REPLLOG AT reply)."""
        from ..resp.message import Bulk
        return Arr([Bulk(e.name), *e.args])


class MergedReplLog:
    """One HLC-ordered view over per-shard repl-log SEGMENTS (the
    shard-per-core serving plane, server/serve_shards.py).

    Each serve worker owns a keyspace shard; its locally-executed writes
    append to that shard's segment (mirrored parent-side in ack order,
    so every segment's uuids are strictly increasing).  Uuids are minted
    centrally by the parent HLC at ROUTE time, so the sorted union of
    the segments is exactly the uuid sequence a single-loop node would
    have produced — the push loop merge-sorts the segments back into
    one stream and the replication protocol (watermarks, REPLACK
    beacons, partial-resync decisions) is unchanged on the wire.

    Emission gating: an entry is VISIBLE only below the floor — the
    smallest write uuid minted but not yet landed (acked) by its shard
    worker.  A later ack can never introduce an entry below the floor
    (workers land their routed commands in mint order), so the merged
    stream is strictly increasing by construction; `pending_high` keeps
    `last_uuid` covering in-flight writes so the push loop never
    declares the stream drained (and never sends a REPLACK beacon the
    peer could fast-forward over un-landed ops).

    The parent's own barrier-plane writes (MEET/FORGET and any other
    loop-executed command) land synchronously in `self.local` — segment
    index n_shards — through the normal `push` entry point."""

    def __init__(self, n_shards: int, cap_bytes: int = ReplLog.DEFAULT_CAP):
        self.cap = cap_bytes
        self.segments = [ReplLog(cap_bytes) for _ in range(n_shards + 1)]
        self.local = self.segments[n_shards]
        # plane callbacks, installed by ServeShardPlane: floor() -> the
        # smallest minted-but-unlanded write uuid (None = nothing in
        # flight); pending_high() -> the NEWEST such uuid (0 = none)
        self.floor = lambda: None
        self.pending_high = lambda: 0
        # watermark fences (boot-restore / reset_for_full_resync set
        # these through the same attribute names ReplLog exposes)
        self._fence_last = 0
        self._fence_evicted = 0

    # ----------------------------------------------------- ReplLog surface

    def __len__(self) -> int:
        return sum(len(s) for s in self.segments)

    @property
    def total_bytes(self) -> int:
        return sum(s.total_bytes for s in self.segments)

    @property
    def first_uuid(self) -> int:
        firsts = [s.first_uuid for s in self.segments if len(s)]
        return min(firsts) if firsts else 0

    @property
    def landed_last_uuid(self) -> int:
        """Newest uuid actually LANDED in a segment (or fenced): what a
        full-sync dump may record as its watermark — unlike `last_uuid`
        it excludes minted-but-in-flight writes, whose effects are not
        yet in any exportable state."""
        return max(max(s.last_uuid for s in self.segments),
                   self._fence_last)

    @property
    def last_uuid(self) -> int:
        """Newest uuid this node has COMMITTED to its stream: landed
        entries, fences, and minted-but-in-flight writes (the push loop
        must not consider the stream drained below those)."""
        return max(self.landed_last_uuid, self.pending_high())

    @last_uuid.setter
    def last_uuid(self, uuid: int) -> None:
        self._fence_last = uuid

    @property
    def evicted_up_to(self) -> int:
        """A resume below ANY segment's eviction horizon is gappy in the
        merged stream, so the merged horizon is the max."""
        return max(max(s.evicted_up_to for s in self.segments),
                   self._fence_evicted)

    @evicted_up_to.setter
    def evicted_up_to(self, uuid: int) -> None:
        self._fence_evicted = uuid

    def push(self, uuid: int, name: bytes, args: list) -> None:
        """Barrier-plane write (executed on the parent loop)."""
        self.local.push(uuid, name, args)

    def can_resume_from(self, uuid: int) -> bool:
        return uuid >= self.evicted_up_to

    def _visible(self, uuid: int) -> bool:
        f = self.floor()
        return f is None or uuid < f

    def next_after(self, uuid: int) -> Optional[ReplEntry]:
        """Merge-sort step: the smallest VISIBLE uuid > `uuid` across
        all segments.  `prev_uuid` stays the per-segment chain — in the
        merged stream a segment's prev is always <= the merged cursor
        (it was emitted earlier), so the peer's gap check only fires on
        true eviction gaps, exactly as on a single-segment stream."""
        best: Optional[ReplEntry] = None
        for s in self.segments:
            e = s.next_after(uuid)
            if e is not None and (best is None or e.uuid < best.uuid):
                best = e
        if best is not None and not self._visible(best.uuid):
            return None
        return best

    def run_after(self, uuid: int, max_n: int,
                  max_bytes: Optional[int] = None) -> list:
        """The maximal SINGLE-SEGMENT run after `uuid` that preserves
        the merged HLC order: start at the globally smallest visible
        uuid > `uuid`, extend within that entry's segment while every
        further entry stays below BOTH the floor and every other
        segment's next pending uuid.  Concatenated runs therefore
        replay to exactly the per-op merged stream (`next_after`
        repeated) — the property the batch wire protocol's run tests
        pin — while shard-per-core serving feeds whole per-shard runs
        to the batch path without re-sorting per op."""
        cands = []
        for s in self.segments:
            e = s.next_after(uuid)
            if e is not None:
                cands.append((e.uuid, s))
        if not cands:
            return []
        cands.sort(key=lambda c: c[0])
        best_seg = cands[0][1]
        bound = cands[1][0] if len(cands) > 1 else None
        f = self.floor()
        if f is not None:
            bound = f if bound is None else min(bound, f)
        run = best_seg.run_after(uuid, max_n, max_bytes)
        if bound is not None:
            for k, e in enumerate(run):
                if e.uuid >= bound:
                    return run[:k]
        return run

    def at(self, uuid: int) -> Optional[ReplEntry]:
        for s in self.segments:
            e = s.at(uuid)
            if e is not None:
                return e
        return None

    def uuids(self) -> list[int]:
        out: list[int] = []
        for s in self.segments:
            out.extend(s.uuids())
        out.sort()
        return out

    def entry_as_msg(self, e: ReplEntry) -> Msg:
        return Arr([Bulk(e.name), *e.args])
