"""Server core: node state, command dispatch, repl-log, event bus, IO loop.

The control plane of a constdb-tpu node (capability parity with reference
src/server.rs, src/cmd.rs, src/link.rs).  Compute-heavy bulk merges are
delegated to engine/ (the MergeEngine boundary); this package is the
single-writer command executor around it.
"""

from .node import Node
from .repl_log import ReplLog
from .events import EventBus, EVENT_REPLICATED, EVENT_REPLICA_ACKED, EVENT_DELETED

__all__ = [
    "Node", "ReplLog", "EventBus",
    "EVENT_REPLICATED", "EVENT_REPLICA_ACKED", "EVENT_DELETED",
]
