"""Node: the single-writer state of one constdb-tpu process.

Capability parity with the reference's `Server` struct (reference
src/server.rs:27-53): node identity, HLC uuid source, keyspace, repl-log
ring, event bus, replica membership, GC.  All mutation happens on one
asyncio event loop (the reference's main-thread discipline, server.rs:128-131);
IO concurrency lives in server/io.py, bulk merge compute in engine/.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from ..engine.cpu import CpuMergeEngine
from ..store.keyspace import KeySpace
from ..utils.hlc import HLC
from .events import EVENT_REPLICATED, EventBus
from .repl_log import ReplLog


@dataclass
class NodeStats:
    """Per-node counters folded into INFO (reference src/stats.rs)."""

    cmds_processed: int = 0
    cmds_replicated: int = 0
    net_in_bytes: int = 0
    net_out_bytes: int = 0
    # replication-link traffic, also included in the net totals (the
    # reference counts every socket byte through its buffers —
    # buf_read.rs:218-236, buf_write.rs:165-183; round 1 only counted
    # client connections, leaving the dominant flow invisible)
    repl_in_bytes: int = 0
    repl_out_bytes: int = 0
    connections_accepted: int = 0
    current_clients: int = 0
    # steady-state pull-path coalescing (replica/coalesce.py): frames
    # folded into columnar micro-batches, batches landed, and frames
    # that fell back to the exact per-key path (barriers)
    repl_frames_coalesced: int = 0
    repl_coalesce_flushes: int = 0
    repl_apply_barriers: int = 0
    # columnar wire protocol (replica/wire.py REPLBATCH): steady-state
    # stream bytes written by the push loop's aggregated flushes (frames
    # only — snapshots/acks ride repl_out_bytes), batch frames
    # sent/received with the op runs they covered, and receiver-side
    # payload decode failures (each one demotes that peer to per-frame
    # delivery, loudly)
    repl_wire_bytes_out: int = 0
    repl_wire_batches_out: int = 0
    repl_wire_batch_frames_out: int = 0
    repl_wire_batches_in: int = 0
    repl_wire_batch_frames_in: int = 0
    repl_wire_demotions: int = 0
    # broadcast plane (round 17): encode-once run cache reuse across the
    # push-loop fan-out (replica/encode_cache.py; the resident bytes
    # gauge reads node.wire_cache live), and negotiated stream
    # compression accounting — raw payload bytes vs the framed bytes
    # that actually shipped (REPLBATCH payloads over the floor; the
    # ratio rides INFO as repl_compress_ratio)
    repl_encode_cache_hits: int = 0
    repl_encode_cache_misses: int = 0
    repl_comp_raw_bytes: int = 0
    repl_comp_wire_bytes: int = 0
    # anti-entropy resyncs SENT by this node's push legs
    # (replica/link.py): digest-negotiated deltas vs full snapshots,
    # the delta payload bytes that replaced them, and digest rounds run
    repl_delta_syncs: int = 0
    repl_delta_bytes: int = 0
    repl_full_syncs: int = 0
    repl_digest_rounds: int = 0
    # replica-link connections re-established after a drop (every
    # _install beyond a link's first, dialed or adopted — replica/
    # link.py).  Per-peer counts ride the INFO replication section.
    repl_reconnects: int = 0
    # client-serving coalescing (server/serve.py): pipelined client
    # commands folded into columnar micro-batches, batches landed,
    # commands that acted as ordered barriers (reads / non-plannable
    # writes / admin inside a coalesced chunk), and a sampled ring of
    # plan→land reply latencies (seconds) surfaced as p50/p99 in INFO
    serve_msgs_coalesced: int = 0
    serve_flushes: int = 0
    serve_barriers: int = 0
    # the coalesced READ plane (round 18, server/serve.py read planner):
    # key-scoped reads served from planned read batches (batched key
    # resolution + vectorized family gathers + the versioned reply
    # cache) instead of acting as per-command barriers, and pending-run
    # lands forced by a read batch needing read-your-writes.  The reply
    # cache's own hit/miss/byte/invalidation gauges live on
    # node.read_cache (server/read_cache.py); sharded nodes fold worker
    # deltas into the parent's cache counters (server/serve_shards.py).
    serve_reads_coalesced: int = 0
    serve_read_flushes: int = 0
    # native intake stage (native/intake.cpp + server/io.py): pipelined
    # chunks split+classified by the C scanner in one call, and the
    # command frames it emitted as opcodes (CONSTDB_NATIVE_INTAKE=0 or a
    # missing extension pins both to zero — the pure path served)
    native_intake_chunks: int = 0
    native_intake_msgs: int = 0
    serve_lat: deque = field(default_factory=lambda: deque(maxlen=2048))
    # overload governance (server/overload.py + server/io.py +
    # replica/link.py): client data writes shed at the maxmemory soft
    # watermark, hard-watermark reclaim sweeps, slow-reading clients
    # disconnected at the reply-buffer cap, and push loops paused on a
    # full per-peer replication window
    oom_shed_writes: int = 0
    oom_hard_reclaims: int = 0
    client_outbuf_disconnects: int = 0
    repl_window_pauses: int = 0
    # client-assisted caching (server/tracking.py): invalidation keys
    # pushed to tracked RESP3 connections, push frames written, and
    # slow trackers demoted to untracked at the outbuf cap
    tracking_invalidations_sent: int = 0
    tracking_pushes: int = 0
    tracking_demotions: int = 0
    merges: int = 0
    merge_rows: int = 0
    merge_secs: float = 0.0
    flush_secs: float = 0.0
    gc_freed: int = 0
    start_time: float = 0.0
    extra: dict = field(default_factory=dict)


class CounterUndoLog:
    """Locally-originated counter steps this node can still UNDO.

    Grounded in "The Only Undoable CRDTs are Counters" (PAPERS.md, arXiv
    2006.10494): the PN-counter is the one family whose ops admit a sound
    inverse — applying the negated delta commutes with every concurrent
    op and converges mesh-wide like any increment.  Each local INCR/DECR
    (and each CNTUNDO, so undo-of-undo is redo) records (uuid → key,
    delta) here; `CNTUNDO key [uuid]` resolves its target against this
    log and replicates the inverse as an ordinary absolute-total CNTSET.

    Node-local on purpose: a slot is a single-writer register, so only
    the op's ORIGIN can soundly invert it — a remote node undoing it
    would write someone else's slot.  Bounded (CONSTDB_UNDO_WINDOW ops,
    FIFO eviction) and not snapshot-persisted: after eviction or a
    restart the op reports "evicted", never a wrong inverse.
    """

    __slots__ = ("cap", "_ops", "_by_key", "_order")

    def __init__(self, cap: Optional[int] = None) -> None:
        if cap is None:
            from ..conf import env_int
            cap = env_int("CONSTDB_UNDO_WINDOW", 4096)
        self.cap = max(1, cap)
        self._ops: dict[int, list] = {}      # uuid -> [key, delta, undone]
        self._by_key: dict[bytes, list] = {}  # key -> uuid stack (newest last)
        self._order: deque[int] = deque()     # FIFO eviction order

    def record(self, uuid: int, key: bytes, delta: int,
               inverse: bool = False) -> None:
        """`inverse=True` marks the record as an undo's own inverse op:
        a BARE `CNTUNDO key` walks user ops only (two bare undos revert
        two increments, they do not ping-pong); undoing an inverse —
        redo — takes its explicit uuid."""
        self._ops[uuid] = [key, delta, False, inverse]
        self._by_key.setdefault(key, []).append(uuid)
        self._order.append(uuid)
        while len(self._order) > self.cap:
            old = self._order.popleft()
            ent = self._ops.pop(old, None)
            if ent is not None:
                stack = self._by_key.get(ent[0])
                if stack is not None:
                    try:
                        stack.remove(old)
                    except ValueError:
                        pass
                    if not stack:
                        del self._by_key[ent[0]]

    def resolve(self, key: bytes, uuid: Optional[int] = None):
        """The undo target: `(uuid, delta)` of the op to invert — the
        explicit uuid (any not-yet-undone record, inverses included:
        that is redo), or the newest not-yet-undone USER op on `key`
        (classic stack undo).  None when there is nothing to undo (the
        command surfaces the precise reason)."""
        if uuid is not None:
            ent = self._ops.get(uuid)
            if ent is None or ent[0] != key or ent[2]:
                return None
            return uuid, ent[1]
        for u in reversed(self._by_key.get(key, ())):
            ent = self._ops[u]
            if not ent[2] and not ent[3]:
                return u, ent[1]
        return None

    def known(self, uuid: int) -> bool:
        return uuid in self._ops

    def mark_undone(self, uuid: int) -> None:
        ent = self._ops.get(uuid)
        if ent is not None:
            ent[2] = True


class Node:
    def __init__(self, node_id: int = 0, alias: str = "", addr: str = "",
                 engine=None, repl_log_cap: int = ReplLog.DEFAULT_CAP,
                 clock=None):
        self.node_id = node_id
        self.alias = alias
        self.addr = addr
        self.hlc = HLC() if clock is None else HLC(clock)
        self.ks = self._make_keyspace()
        self.repl_log = ReplLog(repl_log_cap)
        self.events = EventBus()
        self.engine = engine if engine is not None else CpuMergeEngine()
        self.stats = NodeStats()
        # undoable local counter ops (CNTUNDO — server/commands.py)
        self.undo = CounterUndoLog()
        # overload governance: memory accounting + maxmemory watermarks
        # (server/overload.py; env-configured here, ServerApp / shard
        # workers override via governor.configure)
        from .overload import OverloadGovernor
        self.governor = OverloadGovernor(self)
        from ..replica.manager import ReplicaManager
        self.replicas = ReplicaManager()
        # encode-once run cache: finished wire encodings shared across
        # the push-loop fan-out (replica/encode_cache.py; a registered
        # used_memory source — server/overload.py).  Env-configured
        # here; ServerApp overrides via wire_cache.configure.
        from ..conf import env_int
        from ..replica.encode_cache import RunEncodeCache
        self.wire_cache = RunEncodeCache(
            max(0, env_int("CONSTDB_ENCODE_CACHE_MB", 16)) << 20)
        # versioned hot-key reply cache (server/read_cache.py): finished
        # RESP reply bytes served by the coalescer's read planner while
        # a key's state is provably unchanged.  Invalidated at every
        # mutation intake (commands.execute/apply_replicated per-op,
        # merge_batch/merge_batches for every batched path) and a
        # registered used_memory source (server/overload.py).  A shard
        # worker's Node owns its own cache — each worker invalidates
        # exactly its shard.
        from .read_cache import ReadReplyCache
        self.read_cache = ReadReplyCache(
            max(0, env_int("CONSTDB_READ_CACHE_MB", 16)) << 20)
        # bumped by reset_for_full_resync; replica links stamp it at
        # connection install and refuse stale-epoch REPLACK beacons (a
        # beacon from a pre-wipe stream would re-advance a zeroed pull
        # watermark past ops the wipe discarded)
        self.reset_epoch = 0
        # the ServerApp driving this node's IO, when one exists
        self.app = None
        # durable op log (persist/oplog.py) when AOF is enabled — armed
        # by server/io.py AFTER boot recovery; every repl-log append
        # (replicate_cmd, the serve coalescer's push_many, the sharded
        # ack mirror) and every replicated-intake land mirrors into it
        self.oplog = None
        # the shard-per-core serving plane (server/serve_shards.py) when
        # CONSTDB_SERVE_SHARDS > 1; None = the exact single-loop path.
        # With a plane active this node's ks/engine hold NO data — every
        # data command executes inside the shard worker owning its key,
        # and self.repl_log is the plane's MergedReplLog view.
        self.serve_plane = None
        # cluster mode (cluster/slots.py ClusterState) when
        # CONSTDB_CLUSTER=1 — armed by server/io.py before serving; None
        # = the exact pre-cluster single-group node (every hot-path gate
        # is one `is None` test)
        self.cluster = None
        # RESP3 client tracking (server/tracking.py): the invalidation
        # fan-out to tracked client connections.  Always constructed
        # (empty dicts), never active until a CLIENT TRACKING on — every
        # hot-path tap gates on `.active`, one attribute test.
        from .tracking import TrackingRegistry
        self.tracking = TrackingRegistry(self)

    def _make_keyspace(self) -> KeySpace:
        """Fresh keyspace with the node's event wiring (shared by boot and
        reset_for_full_resync so the hookup cannot diverge)."""
        ks = KeySpace()
        from .events import EVENT_DELETED
        ks.on_key_delete = lambda: self.events.trigger(EVENT_DELETED)
        return ks

    # ------------------------------------------------------------ execution

    def execute(self, req, client=None, uuid=None):
        """One client command, fully (parse → run → replicate).  `uuid`:
        a pre-minted HLC uuid (shard-per-core serving — the routing
        parent is the clock authority; see commands.execute)."""
        from .commands import execute
        return execute(self, req, client, uuid=uuid)

    def apply_replicated(self, name: bytes, args: list, origin_nodeid: int,
                         uuid: int):
        """One command from a peer's replication stream."""
        from .commands import apply_replicated
        return apply_replicated(self, name, args, origin_nodeid, uuid)

    def replicate_cmd(self, uuid: int, name: bytes, args: list) -> None:
        """Append to the repl_log and wake pushers (reference
        src/server.rs:270-288).  The durable op log mirrors the append
        BEFORE the pusher wake: under fsync=always the emission floor
        holds the entry back until its group commit lands anyway, and
        the mirror-first order is what makes the chaos journal's
        obligation set equal the on-disk set (persist/oplog.py)."""
        self.repl_log.push(uuid, name, args)
        if self.oplog is not None:
            self.oplog.append_local(uuid, name, args)
        self.events.trigger(EVENT_REPLICATED, uuid)

    # ------------------------------------------------------------------- GC

    def gc_horizon(self) -> int:
        """Tombstones at or below this uuid are collectable: every live peer's
        stream has passed it (reference replica/replica.rs:87-89 min over
        uuid_he_sent; standalone nodes collect up to their own clock).

        A mid-flight slot migration additionally clamps the horizon at
        its start pin (cluster/slots.py pin_gc): a delete landing during
        the handoff must still be a visible TOMBSTONE in the final
        export, or the moved copy resurrects the key across the
        ownership flip (docs/INVARIANTS.md "Slot ownership laws")."""
        horizon = None
        if self.replicas is not None:
            horizon = self.replicas.min_uuid()
        if horizon is None:
            horizon = self.hlc.current
        cl = self.cluster
        if cl is not None:
            # the GC pulse doubles as the import-window staleness sweep:
            # a migration source that died after SETSLOT IMPORTING must
            # not pin this node's tombstone GC (or keep the slot's
            # partial copy serving) forever
            import time
            cl.expire_stale_imports(time.monotonic())
            pin = cl.gc_pin()
            if pin is not None and pin < horizon:
                horizon = pin
        return horizon

    def gc(self) -> int:
        self.ensure_flushed()
        freed = self.ks.gc(self.gc_horizon())
        self.stats.gc_freed += freed
        return freed

    # ------------------------------------------------------------ merge path

    def merge_batch(self, batch) -> None:
        """Bulk CRDT merge via the configured MergeEngine (snapshot ingest /
        replica catch-up — the reference's per-key db.merge_entry loop).
        With a device-resident engine, merged state stays on the device
        between calls; it flushes to the host lazily before the next read
        (`ensure_flushed`)."""
        import time
        self._invalidate_reads((batch,))
        t0 = time.perf_counter()
        st = self.engine.merge(self.ks, batch)
        self.stats.merge_secs += time.perf_counter() - t0
        self.stats.merges += 1
        self.stats.merge_rows += batch.n_rows
        self._dump_stale()
        return st

    def _invalidate_reads(self, batches) -> None:
        """Reply-cache invalidation for every BATCHED mutation intake —
        snapshot/delta ingest, coalesced replication apply, columnar
        wire batches, serve-coalescer runs, oplog replay all ride
        merge_batch/merge_batches, so hooking here (BEFORE the merge
        lands) is what makes invalidate-before-visible complete
        (server/read_cache.py) — and the tracked-client push stream
        (server/tracking.py) taps the same seam with its own gate, so
        wire invalidation is complete by the same construction."""
        tr = self.tracking
        if tr is not None and tr.active:
            for b in batches:
                tr.invalidate_keys(b.keys)
                if b.del_keys:
                    tr.invalidate_keys(b.del_keys)
        rc = self.read_cache
        if not len(rc):
            return
        for b in batches:
            rc.invalidate_keys(b.keys)
            if b.del_keys:
                rc.invalidate_keys(b.del_keys)

    def _dump_stale(self) -> None:
        """Bulk-merged state bypasses the repl_log, so a cached full-sync
        dump plus a log tail would silently omit it: force the next peer to
        get a fresh dump (persist/share.py reuse rule covers only LOGGED
        writes)."""
        app = self.app
        if app is not None and getattr(app, "shared_dump", None) is not None:
            app.shared_dump.invalidate()

    def merge_batches(self, batches: list, logged: bool = False) -> None:
        """Merge a GROUP of columnar batches in one engine call when the
        engine supports it (engine/tpu.py merge_many reduces aligned groups
        in one fused [R, N] device pass, and unaligned groups still share
        one state roundtrip per family); per-batch merges otherwise.

        A SINGLE batch also routes through merge_many when its rows may
        repeat per slot (a serve/stream coalescer flush): that is where
        both engines pick the vectorized host micro-strategy
        (engine/hostbatch.py) — the per-batch `merge` entry point is the
        CPU engine's per-row REFERENCE path, dozens of times slower at
        op-stream scale."""
        if not batches:
            return
        if not hasattr(self.engine, "merge_many") or \
                (len(batches) == 1 and batches[0].rows_unique_per_slot):
            for b in batches:
                self.merge_batch(b)
            return
        import time
        self._invalidate_reads(batches)
        t0 = time.perf_counter()
        self.engine.merge_many(self.ks, batches)
        self.stats.merge_secs += time.perf_counter() - t0
        self.stats.merges += 1
        self.stats.merge_rows += sum(b.n_rows for b in batches)
        if len(batches) > 1:
            x = self.stats.extra
            x["group_merges"] = x.get("group_merges", 0) + 1
            x["group_merge_batches"] = \
                x.get("group_merge_batches", 0) + len(batches)
        if not logged:
            # `logged` batches (the serve coalescer's runs) are appended
            # to the repl_log in full, so a cached full-sync dump plus a
            # log tail still covers them — only UNLOGGED bulk merges must
            # force the next peer onto a fresh dump (persist/share.py
            # reuse rule)
            self._dump_stale()

    def merge_stream_batch(self, builder, frames: int) -> None:
        """Land one coalesced replication micro-batch (the steady-state
        pull path, replica/coalesce.py) through the same engine seam
        snapshot ingest uses.  `builder.finalize()` evaluates the
        element-plane key-delete rule against LIVE host dt columns, so
        unflushed device state COVERING the env plane must flush first —
        the narrow form of the flush-before-read discipline
        `apply_replicated` applies per frame.  A steady-state resident
        engine keeps env host-authoritative (engine/tpu.py micro path),
        so consecutive stream batches merge in place on device with no
        flush round-trip between them."""
        self.ensure_flushed_for(("env",))
        self.merge_batches([builder.finalize()])
        self.stats.repl_frames_coalesced += frames
        self.stats.repl_coalesce_flushes += 1

    def merge_serve_batch(self, builder, msgs: int) -> None:
        """Land one coalesced client-serving micro-batch (the pipelined
        RESP path, server/serve.py) through the same engine seam the
        replication coalescer rides.  Same narrow flush-before-finalize
        discipline as merge_stream_batch (`builder.finalize()` reads
        live env dt columns only; the serve planners' own reads flush
        through the coalescer's probe paths).  The run is fully
        repl-logged by the caller, so logged=True keeps the shared
        full-sync dump reusable."""
        self.ensure_flushed_for(("env",))
        self.merge_batches([builder.finalize()], logged=True)
        self.stats.serve_msgs_coalesced += msgs
        self.stats.serve_flushes += 1

    def reset_for_full_resync(self, keep_link=None) -> None:
        """Wipe local CRDT state and rejoin as a fresh node (the receive
        side of the fullsync `reset` flag — replica/link.py).  Used when a
        pusher excluded us from its GC horizon past its repl_log window:
        tombstones we never saw are physically gone mesh-wide, so keys we
        still hold live would resurrect through any plain merge.  Clears
        the keyspace, the repl_log (our own unsynced ops describe state
        being discarded), and every pull watermark (what we applied from
        other peers was part of the wiped store); membership survives so
        the mesh re-forms around us.

        Every OTHER live connection is kicked so its peer re-handshakes
        from the zeroed watermark (resume 0 → full or from-zero partial
        resync).  Merely zeroing is not enough: an idle surviving stream
        re-sends nothing, and its REPLACK beacon would quietly re-advance
        the zeroed watermark past ops the wipe discarded — the epoch bump
        makes links drop such stale-stream beacons (replica/link.py).
        `keep_link` (the link delivering the reset snapshot) stays up."""
        engine = self.engine
        if hasattr(engine, "discard_resident"):
            engine.discard_resident()
        # every cached reply describes wiped state (and its stamps hold
        # kids of the discarded keyspace object)
        self.read_cache.clear()
        # ... and so does every tracked client's near-cache: flush-all
        # push before the wipe is visible (server/tracking.py)
        tr = self.tracking
        if tr is not None and tr.active:
            tr.flush_all()
        cap = self.repl_log.cap
        fence = max(self.repl_log.last_uuid, self.hlc.current)
        self.ks = self._make_keyspace()
        self.repl_log = ReplLog(cap)
        # Fence the fresh (empty) log at the pre-wipe watermark: a peer
        # resuming below it must get a FULL snapshot of the post-reset
        # store — with last_uuid/evicted_up_to left at 0,
        # can_resume_from(old_watermark) would be true and the push loop
        # would serve a PARTSYNC of nothing, permanently omitting the
        # resynced keyspace (same rule as the boot-restore path,
        # server/io.py start_node).
        self.repl_log.last_uuid = fence
        self.repl_log.evicted_up_to = fence
        if self.oplog is not None:
            # every logged record describes discarded state; the log is
            # truncated and recovery is fenced so a crash before the
            # post-resync rewrite lands boots empty + full-syncs instead
            # of resurrecting pre-wipe keys (persist/oplog.py on_wipe —
            # it also reinstalls the emission floor on the fresh ring)
            self.oplog.on_wipe(fence)
        self._kick_peers_after_wipe(keep_link)

    def _kick_peers_after_wipe(self, keep_link=None) -> None:
        """Post-wipe peer bookkeeping shared by the single-loop reset
        above and the serve plane's reset (server/serve_shards.py):
        epoch bump (stale-beacon fence), watermark zeroing, and a kick
        for every other live connection."""
        self.reset_epoch += 1
        if self.replicas is not None:
            for m in self.replicas.peers.values():
                m.uuid_he_sent = 0
                m.uuid_he_acked = 0
                link = m.link
                if link is not None and link is not keep_link and \
                        hasattr(link, "kick"):
                    link.kick()
        self._dump_stale()

    def ensure_flushed(self) -> None:
        """Sync device-resident merge state back to the host keyspace
        before any read/write of the numeric plane."""
        engine = self.engine
        if getattr(engine, "needs_flush", False):
            import time
            t0 = time.perf_counter()
            engine.flush(self.ks)
            self.stats.flush_secs += time.perf_counter() - t0

    def ensure_flushed_for(self, families) -> None:
        """Flush only when unflushed device-resident state actually
        covers one of `families` — the narrow read-barrier for callers
        that provably read nothing else (docs/INVARIANTS.md
        flush-before-read law).  Engines without the staleness probe
        take the full flush."""
        engine = self.engine
        if getattr(engine, "needs_flush", False):
            stale = getattr(engine, "host_stale", None)
            if stale is None or stale(families):
                self.ensure_flushed()

    def tensor_read(self, kid: int):
        """One tensor key's strategy reduction, DEVICE-FIRST: a steady
        resident engine reduces straight from its payload pools —
        dirty payloads never round-trip through the host, which is the
        tensor family's reason to exist (the TENSOR.GET path;
        commands.execute narrows its flush for exactly this).  Other
        engines flush the tensor plane narrowly and run the host
        reference reduction."""
        engine = self.engine
        if getattr(engine, "steady", False) and \
                getattr(engine, "resident", False):
            return engine.tensor_read_many(self.ks, (kid,))[kid]
        self.ensure_flushed_for(("tns",))
        return self.ks.tensor_read(kid)

    def canonical(self) -> dict:
        self.ensure_flushed()
        return self.ks.canonical()
