"""Event bus: wakes replica pushers when there is new work.

Capability parity with the reference's broadcast-based producer/consumer
(reference src/server.rs:477-545: EventsProducer over tokio::sync::broadcast,
consumers filter by bitmask).  Redesigned for the asyncio runtime: each
consumer owns an asyncio.Event; `trigger` sets the events of every consumer
whose mask matches.  Consumers that are slow simply coalesce wakeups (the
reference's lagged-broadcast behavior), so the bus never grows unbounded.
"""

from __future__ import annotations

import asyncio
from typing import Optional

EVENT_REPLICATED = 1       # a new entry hit the repl_log
EVENT_REPLICA_ACKED = 2    # a peer advanced an ack watermark
EVENT_DELETED = 4          # a key-level tombstone was recorded
EVENT_PULL_LANDED = 8      # a peer-stream batch landed (pull watermark
#                            advanced): push loops wake to REPLACK once
#                            per covering batch instead of per heartbeat


class EventsConsumer:
    __slots__ = ("mask", "_ev", "_bus")

    def __init__(self, bus: "EventBus", mask: int):
        self.mask = mask
        self._ev = asyncio.Event()
        self._bus = bus

    async def wait(self, timeout: Optional[float] = None) -> bool:
        """True if woken by an event, False on timeout."""
        try:
            await asyncio.wait_for(self._ev.wait(), timeout)
        except asyncio.TimeoutError:
            return False
        self._ev.clear()
        return True

    def close(self) -> None:
        self._bus._consumers.discard(self)


class EventBus:
    def __init__(self) -> None:
        self._consumers: set[EventsConsumer] = set()
        self.last_replicated_uuid = 0

    def new_consumer(self, mask: int = EVENT_REPLICATED) -> EventsConsumer:
        c = EventsConsumer(self, mask)
        self._consumers.add(c)
        return c

    def trigger(self, kind: int, uuid: int = 0) -> None:
        if kind == EVENT_REPLICATED and uuid:
            self.last_replicated_uuid = uuid
        for c in self._consumers:
            if c.mask & kind:
                c._ev.set()
