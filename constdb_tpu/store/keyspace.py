"""Columnar keyspace — the data plane of a node.

Capability parity with the reference's `DB` + per-key `Object` heap
(reference src/db.rs, src/object.rs, src/type_counter.rs,
src/crdt/lwwhash.rs), redesigned TPU-first: all numeric CRDT state
(envelope times, counter slots, element add/del times) lives in contiguous
numpy columns so bulk merges stage to the device without per-row Python
work.  Indexes from key/member bytes to rows are native C++ hash tables
(native/tables.cpp via utils/native_tables.py) with batch entry points —
the merge engine resolves a million rows in a handful of FFI calls.

Tables:
  keys  — one row per key: enc, ct/mt/dt envelope, expire, register value
          (bytes in a side list) with its (write-time, writer-node), counter
          sum cache.  `key_index` (StrTable) maps key bytes -> row, and row
          ids ARE interner ids (both assign in insertion order).
  cnt   — one row per (key, node) counter slot: val, uuid, base, base_t.
          `cnt_rank_rows` maps node rank -> direct (kid -> row) int32
          array, so slot resolution is a vectorized gather, not a hash
          probe per row.
  el    — one row per set-member / dict-field: add_t, add_node, del_t;
          member/value bytes in side lists.  `member_index` (StrTable)
          interns member bytes; `el_index` (I64Dict) maps
          (kid << MEMBER_BITS | member_id) -> row.  GC marks rows dead
          (kid = -1); `_compact_elements` rebuilds the columns once dead
          rows dominate (no free-list — row ids stay stable between
          compactions, which the batched engine relies on).

Single-op serving methods implement the op-level rules of
crdt/semantics.py; bulk merge goes through engine/ (MergeEngine boundary).
"""

from __future__ import annotations

import heapq
import threading
import zlib
from typing import Iterator, Optional

import numpy as np

from ..crdt import semantics as S
from ..crdt import tensor as T
from ..errors import InvalidType
from ..utils.native_tables import I64Dict, StrTable
from .columns import Columns, TensorCols

_I64 = np.int64

# the CRDT planes a resident merge engine mirrors — the ONE definition the
# command table, the version setter, and the engine all derive from
FAMILIES = ("env", "reg", "cnt", "el", "tns")


def _blen(x) -> int:
    return len(x) if x is not None else 0


class BlobList(list):
    """Side list of optional byte-strings with incremental byte
    accounting into its keyspace's `blob_bytes` gauge.

    Every blob plane (key bytes, register values, element members and
    values) is one of these, so the overload governor's `used_bytes`
    stays exact through EVERY mutation path — the op-path setters, the
    engines' winner-assignment loops, and the flush path's slice writes
    — without instrumenting each call site (there are a dozen across
    engine/hostbatch.py and engine/tpu.py alone, all hot).  Two escape
    hatches exist, both fenced: rebinding the attribute to a plain list
    (only `_compact_elements` does it, adjusting the gauge itself), and
    the list mutators no blob plane uses — those raise loudly below
    instead of silently drifting the gauge, so a future call site must
    add its accounting here first.

    Pickles as a plain list (shard workers ship copies of these in
    `keyspace_state_bytes`; the receiving side owns no gauge)."""

    __slots__ = ("ks",)

    def __init__(self, ks, items=()):
        super().__init__(items)
        ks.blob_bytes += sum(map(_blen, self))
        self.ks = ks

    def append(self, x) -> None:
        self.ks.blob_bytes += _blen(x)
        list.append(self, x)

    def extend(self, it) -> None:
        n0 = len(self)
        list.extend(self, it)
        if len(self) > n0:
            self.ks.blob_bytes += sum(map(_blen,
                                          list.__getitem__(
                                              self, slice(n0, None))))

    def __setitem__(self, i, v) -> None:
        if type(i) is slice:
            old = sum(map(_blen, list.__getitem__(self, i)))
            v = list(v)
            list.__setitem__(self, i, v)
            self.ks.blob_bytes += sum(map(_blen, v)) - old
        else:
            self.ks.blob_bytes += _blen(v) - _blen(list.__getitem__(self, i))
            list.__setitem__(self, i, v)

    def _unaccounted(self, *_a, **_k):
        raise NotImplementedError(
            "unaccounted BlobList mutation — this mutator would drift "
            "KeySpace.blob_bytes silently; add byte accounting to "
            "BlobList before using it on a blob plane")

    # no blob plane uses these today (the accounting property test
    # would not catch a silent bypass, so fail loudly instead)
    pop = remove = insert = clear = _unaccounted
    __delitem__ = __iadd__ = __imul__ = _unaccounted

    def __reduce__(self):
        return (list, (list(self),))


class _KeyCols(Columns):
    def __init__(self) -> None:
        super().__init__(
            {"enc": np.int8, "ct": _I64, "mt": _I64, "dt": _I64, "expire": _I64,
             "rv_t": _I64, "rv_node": _I64, "cnt_sum": _I64},
            cap=8096,  # parity: reference db.rs DB_INITIAL_SIZE
        )


class _CntCols(Columns):
    # val  = the writer node's LIFETIME cumulative total (LWW register @ uuid)
    # base = the total observed by the latest counter delete (LWW @ base_t)
    # visible contribution of a slot = val - base
    def __init__(self) -> None:
        super().__init__({"kid": _I64, "node": _I64, "val": _I64, "uuid": _I64,
                          "base": _I64, "base_t": _I64}, cap=4096)


class _ElCols(Columns):
    def __init__(self) -> None:
        super().__init__({"kid": _I64, "add_t": _I64, "add_node": _I64, "del_t": _I64}, cap=8192)


class KeySpace:
    NODE_RANK_BITS = 20  # up to ~1M distinct node ids per cluster lifetime
    MEMBER_BITS = 32     # up to ~4G distinct member byte-strings
    NEUTRAL_T = S.NEUTRAL_T
    # dense per-rank counter windows convert to a hash once they would
    # span > DENSE_FLOOR kids at < 1/MIN_FILL occupancy (sparse wide-range
    # ranks must not cost O(kid range) host RAM)
    CNT_WINDOW_MIN_FILL = 8
    CNT_WINDOW_DENSE_FLOOR = 1 << 16

    def __init__(self) -> None:
        self.keys = _KeyCols()
        # exact byte total of every blob side list (key bytes, register
        # values, element members/values) — maintained incrementally by
        # BlobList through every mutation path; `used_bytes` folds it
        # into the overload governor's memory accounting
        self.blob_bytes = 0
        self.key_bytes: list[bytes] = BlobList(self)
        self.key_index = StrTable(8096)
        self.reg_val: list[Optional[bytes]] = BlobList(self)
        # per-CRDT-plane write versions, bumped by op-path writes: a
        # device-resident merge engine drops ONLY the mirrors of planes
        # that actually changed (engine/tpu.py; a global version made
        # mixed traffic re-upload every table per frame)
        self.fam_ver: dict[str, int] = dict.fromkeys(FAMILIES, 0)

        self.cnt = _CntCols()
        # per-rank direct (kid -> cnt row) index windows: counter slot
        # resolution is a vectorized gather (engine) or one array read
        # (op path) instead of a hash probe per row.  Each rank holds
        # (base, int32 array) covering only the kid RANGE it has touched
        # (-1 = absent), so a node owning a handful of high-kid slots
        # costs KBs, not O(keys.n).  A rank whose touched kids are SPARSE
        # over a wide range (occupancy below 1/CNT_WINDOW_MIN_FILL of a
        # window past CNT_WINDOW_DENSE_FLOOR entries) falls back to an
        # I64Dict in `cnt_rank_hash` instead — O(slots) RAM, not
        # O(kid range) (round-5 advisor).
        self.cnt_rank_rows: dict[int, tuple[int, np.ndarray]] = {}
        self.cnt_rank_hash: dict[int, object] = {}
        self.cnt_rank_live: dict[int, int] = {}
        # per-kid row lists are derived lazily from the columns (bulk merges
        # append millions of rows; only point reads need the lists)
        self.cnt_rows_by_kid: dict[int, list[int]] = {}
        self._cnt_synced = 0
        self.node_rank: dict[int, int] = {}
        self.node_ids: list[int] = []

        self.el = _ElCols()
        self.el_member: list[Optional[bytes]] = BlobList(self)
        self.el_val: list[Optional[bytes]] = BlobList(self)
        self.member_index = StrTable(8192)
        self.el_index = I64Dict(8192)
        self.el_rows_by_kid: dict[int, list[int]] = {}
        self._el_synced = 0
        self.el_dead = 0
        # bumped by _compact_elements (the ONLY operation allowed to
        # re-identify element rows).  Row ids are stable between bumps —
        # the batched engine stages row indices on a worker thread and
        # scatters into them at dispatch, so it pins this counter across
        # the stage→dispatch window (engine/tpu.py) and fails loudly if a
        # compaction slipped in between.
        self.el_compact_epoch = 0

        # incremental crc32 caches for the anti-entropy digest
        # (store/digest.py): key/member bytes are hashed ONCE, in append
        # order, by key_crcs()/member_crcs() — the per-item Python cost
        # of a digest exchange is amortized over the row's lifetime
        # instead of re-paid per exchange.  _compact_elements drops the
        # member cache (row ids change); keys are never re-identified.
        self._key_crc: Optional[np.ndarray] = None
        self._key_crc_n = 0
        self._member_crc: Optional[np.ndarray] = None
        self._member_crc_n = 0
        # serializes the crc cache grow-and-fill: warm_digest_caches
        # runs in an executor thread while the event loop may sync the
        # same caches inline (digest refinement on another link) —
        # unserialized, interleaved (cache, n) field writes could pair
        # a small-capacity array with a larger synced count
        self._crc_lock = threading.Lock()

        # tensor plane (crdt/tensor.py): contributor slots — one row per
        # (key, writer node) — with the LWW stamp/count columns in `tns`
        # and the payload arrays row-aligned in `tns_payload`.  Config is
        # creation-fixed per key (`tns_meta`); `tns_index` maps
        # (kid << NODE_RANK_BITS) | rank -> row.  Rows are never
        # compacted (slots persist across key tombstones — the envelope
        # ct/dt rule decides visibility, add-wins like registers).
        self.tns = TensorCols()
        self.tns_payload: list[Optional[np.ndarray]] = []
        self.tns_index = I64Dict(256)
        self.tns_meta: dict[int, T.TensorMeta] = {}
        self.tns_rows_by_kid: dict[int, list[int]] = {}
        self._tns_synced = 0
        # running payload-byte gauge (INFO: exact without an O(rows) walk)
        self.tns_bytes = 0
        # slot-merge WINS by strategy name (INFO: merges by strategy)
        self.tns_merges_by_strat: dict[str, int] = {}

        # key-level tombstone record for snapshot DELETES + GC
        # (parity: reference db.rs `deletes` map)
        self.key_deletes: dict[bytes, int] = {}
        # optional hook fired when a key-level tombstone is recorded (the
        # Node routes it to EVENT_DELETED so the GC cron can sweep early)
        self.on_key_delete = None
        # min-heap of (uuid, seq, key, member-or-None): merge and replicated
        # ops enqueue out-of-order timestamps, so a plain FIFO (the
        # reference's LinkedList, db.rs) would stall collection behind one
        # future entry; seq breaks comparison ties before the None member
        self.garbage: list[tuple[int, int, bytes, Optional[bytes]]] = []
        self._garbage_seq = 0

    # ------------------------------------------------------------- versions

    def touch(self, *families: str) -> None:
        """Mark CRDT planes as host-modified (op path / GC)."""
        fv = self.fam_ver
        for f in families:
            fv[f] += 1

    @property
    def version(self) -> int:
        """Aggregate write version (monotonic; back-compat surface)."""
        return sum(self.fam_ver.values())

    @version.setter
    def version(self, _value) -> None:
        """`ks.version += 1` keeps meaning "everything may have changed"."""
        self.touch(*FAMILIES)

    # ------------------------------------------------------------------ keys

    def lookup(self, key: bytes) -> int:
        return self.key_index.lookup(key)

    def n_keys(self) -> int:
        return self.keys.n

    def create_key(self, key: bytes, enc: int, ct: int, dt: int = 0) -> int:
        kid = self.keys.append(enc=enc, ct=ct, mt=0, dt=dt, expire=0,
                               rv_t=0, rv_node=0, cnt_sum=0)
        self.key_bytes.append(key)
        self.reg_val.append(None)
        iid = self.key_index.get_or_insert(key)
        assert iid == kid, f"key index desync: {iid} != {kid}"
        return kid

    def get_or_create(self, key: bytes, enc: int, uuid: int) -> tuple[int, bool]:
        """Existing row (type-checked) or a fresh one created at `uuid`."""
        kid = self.key_index.lookup(key)
        if kid < 0:
            return self.create_key(key, enc, uuid), True
        if int(self.keys.enc[kid]) != enc:
            raise InvalidType()
        return kid, False

    def query(self, key: bytes, uuid: int) -> int:
        """kid or -1; lazily applies a due expiry as a key-level delete
        (parity: reference db.rs:53-66)."""
        kid = self.key_index.lookup(key)
        if kid < 0:
            return -1
        exp = int(self.keys.expire[kid])
        if exp and exp <= uuid and int(self.keys.dt[kid]) < exp:
            # a due expiry is a plain key-level delete at `exp`: dt advances
            # to exp and the usual `ct >= dt` rule decides visibility, so a
            # data write after the deadline resurrects the key (add-wins).
            # (The reference instead calls updated_at here, resurrecting the
            # key it just expired — db.rs:53-66, its own assertion at
            # db.rs:154 is commented out.  Fixed.)
            self.keys.dt[kid] = exp
            if exp > int(self.keys.mt[kid]):
                self.keys.mt[kid] = exp
            self.record_key_delete(key, exp)
            # this is a READ-path host write: without the bump a resident
            # env mirror would flush its older dt back and resurrect the
            # expired key
            self.touch("env")
        return kid

    def alive(self, kid: int) -> bool:
        return S.key_alive(int(self.keys.ct[kid]), int(self.keys.dt[kid]))

    def key_delete_times(self, keys: list) -> np.ndarray:
        """Vectorized key bytes -> current key-level delete time (0 for
        absent keys).  The coalescing replication applier
        (replica/coalesce.py) evaluates the element-plane key-delete rule
        against the LIVE dt at the moment its batch lands — one batched
        native lookup instead of a hash probe per pending frame."""
        kids = self.key_index.lookup_batch(keys)
        out = np.zeros(len(keys), dtype=_I64)
        m = kids >= 0
        if m.any():
            out[m] = self.keys.dt[kids[m]]
        return out

    @staticmethod
    def _crc_sync(cache: Optional[np.ndarray], synced: int, n: int,
                  items) -> tuple[np.ndarray, int]:
        """Grow-and-fill helper for the incremental crc caches: crc32 the
        items appended since the last sync into a uint64 cache array."""
        if cache is None or len(cache) < n:
            cap = 1 << max(n - 1, 1023).bit_length()
            new = np.zeros(cap, dtype=np.uint64)
            if cache is not None and synced:
                new[:synced] = cache[:synced]
            cache = new
        if synced < n:
            crc = zlib.crc32
            cache[synced:n] = np.fromiter(
                (crc(b) if b is not None else 0
                 for b in items[synced:n]),
                dtype=np.uint64, count=n - synced)
        return cache, n

    def key_crcs(self) -> np.ndarray:
        """crc32 of every key's bytes, kid-aligned (the digest partition
        — store/digest.py).  Maintained incrementally in append order:
        each key is hashed once over its lifetime, not once per digest
        exchange."""
        n = self.keys.n
        with self._crc_lock:
            self._key_crc, self._key_crc_n = self._crc_sync(
                self._key_crc, self._key_crc_n, n, self.key_bytes)
            return self._key_crc[:n]

    def member_crcs(self) -> np.ndarray:
        """crc32 of every element row's member bytes, row-aligned (0 for
        GC-dead rows, which digests exclude anyway).  Incremental like
        key_crcs; element compaction re-identifies rows and drops the
        cache (_compact_elements)."""
        n = self.el.n
        with self._crc_lock:
            epoch = self.el_compact_epoch
            cache, cn = self._crc_sync(
                self._member_crc, self._member_crc_n, n, self.el_member)
            if self.el_compact_epoch != epoch:
                # an element compaction interleaved this pass — only
                # possible off-loop (warm_digest_caches in an executor;
                # inline callers run on the loop, where compaction can't
                # preempt).  Rows were re-identified under us: drop the
                # pass instead of storing a misaligned cache (the warm
                # caller discards the return; the next inline sync
                # rebuilds from the compacted columns).
                self._member_crc = None
                self._member_crc_n = 0
                return np.zeros(0, dtype=np.uint64)
            self._member_crc, self._member_crc_n = cache, cn
            return self._member_crc[:n]

    def warm_digest_caches(self) -> None:
        """Fill the incremental digest crc caches — safe to run in an
        executor thread while the event loop serves (replica/link.py
        _local_digest warms off-loop so the FIRST digest on a long-lived
        store doesn't stall the loop on the per-item crc32 backlog over
        every key and member).  Inline syncs serialize on _crc_lock; an
        element compaction interleaving the member pass is ordered by
        the same lock (see _compact_elements / member_crcs)."""
        self.key_crcs()
        self.member_crcs()

    def enc_of(self, kid: int) -> int:
        return int(self.keys.enc[kid])

    def updated_at(self, kid: int, uuid: int) -> None:
        ct, mt, dt = S.updated_at(int(self.keys.ct[kid]), int(self.keys.mt[kid]),
                                  int(self.keys.dt[kid]), uuid)
        self.keys.ct[kid], self.keys.mt[kid], self.keys.dt[kid] = ct, mt, dt

    def envelope(self, kid: int) -> tuple[int, int, int]:
        return int(self.keys.ct[kid]), int(self.keys.mt[kid]), int(self.keys.dt[kid])

    def set_delete_time(self, kid: int, uuid: int) -> None:
        if uuid > int(self.keys.dt[kid]):
            self.keys.dt[kid] = uuid
        if uuid > int(self.keys.mt[kid]):
            self.keys.mt[kid] = uuid

    def expire_at(self, key: bytes, t: int) -> None:
        """Latest expiry wins (max-merge; see semantics.py header)."""
        kid = self.key_index.lookup(key)
        if kid >= 0 and t > int(self.keys.expire[kid]):
            self.keys.expire[kid] = t

    def _enqueue_garbage(self, t: int, key: bytes, member: Optional[bytes]) -> None:
        self._garbage_seq += 1
        heapq.heappush(self.garbage, (t, self._garbage_seq, key, member))

    def enqueue_garbage_bulk(self, ts: list, keys: list, members: list) -> None:
        """Bulk tombstone enqueue.  A snapshot-merge flush queues millions
        of entries, where the per-push path was a top flush cost — but a
        SMALL batch into a huge standing heap must not pay a full O(heap)
        re-heapify either, so pushes win whenever n·log(heap) is cheaper."""
        n = len(ts)
        if not n:
            return
        seq0 = self._garbage_seq
        self._garbage_seq = seq0 + n
        seqs = range(seq0 + 1, seq0 + 1 + n)
        heap = self.garbage
        total = len(heap) + n
        if n * max(total.bit_length(), 1) < total:
            for entry in zip(ts, seqs, keys, members):
                heapq.heappush(heap, entry)
        else:
            heap.extend(zip(ts, seqs, keys, members))
            heapq.heapify(heap)

    def record_key_delete(self, key: bytes, t: int) -> None:
        if self.key_deletes.get(key, -1) < t:
            self.key_deletes[key] = t
            self._enqueue_garbage(t, key, None)
            if self.on_key_delete is not None:
                self.on_key_delete()

    # -------------------------------------------------------------- counters

    def rank_of(self, node: int) -> int:
        """Dense rank for a node id (monotone in registration order)."""
        r = self.node_rank.get(node)
        if r is None:
            r = len(self.node_ids)
            if r >= (1 << self.NODE_RANK_BITS):
                raise OverflowError("too many distinct node ids")
            self.node_rank[node] = r
            self.node_ids.append(node)
        return r

    def cnt_rank_rows_arr(self, rank: int, lo: int,
                          hi: int) -> tuple[int, np.ndarray]:
        """The rank's (base, kid -> cnt row) window, grown (fill -1) to
        cover kids [lo, hi).  Rows are int32 (a keyspace cannot exceed
        2^31 counter slots before exhausting memory ~100x over)."""
        ent = self.cnt_rank_rows.get(rank)
        if ent is not None:
            base, arr = ent
            if lo >= base and hi <= base + len(arr):
                return ent
        # the grown window's geometry comes from the SAME helper the
        # dense-vs-hash decision uses (cnt_rows_assign/_cnt_row) — the
        # predicted cap and the allocated cap cannot drift apart
        nb, cap = self._window_cap(lo, hi, ent)
        new = np.full(cap, -1, dtype=np.int32)
        if ent is not None:
            base, arr = ent
            new[base - nb: base - nb + len(arr)] = arr
        self.cnt_rank_rows[rank] = (nb, new)
        return nb, new

    @staticmethod
    def _window_cap(lo: int, hi: int, ent) -> tuple[int, int]:
        """(base, cap) the dense window would need to cover [lo, hi)."""
        nb = lo & ~1023
        if ent is not None:
            base, arr = ent
            nb = min(nb, base)
            top = max(base + len(arr), hi)
        else:
            top = hi
        return nb, 1 << max(top - nb - 1, 1023).bit_length()

    def _rank_to_hash(self, rank: int):
        """Convert a rank's dense window (if any) to hash mode."""
        h = I64Dict(max(self.cnt_rank_live.get(rank, 0), 16))
        ent = self.cnt_rank_rows.pop(rank, None)
        if ent is not None:
            base, arr = ent
            live = np.nonzero(arr >= 0)[0]
            if len(live):
                h.put_batch(live + base, arr[live].astype(_I64))
        self.cnt_rank_hash[rank] = h
        return h

    def cnt_rows_lookup(self, rank: int, kids: np.ndarray) -> np.ndarray:
        """Vectorized kid -> cnt row for one rank (-1 = absent).  Never
        grows the dense window — pure lookups mask against it instead."""
        h = self.cnt_rank_hash.get(rank)
        if h is not None:
            return h.lookup_batch(kids)
        ent = self.cnt_rank_rows.get(rank)
        if ent is None:
            return np.full(len(kids), -1, dtype=_I64)
        base, arr = ent
        lo = int(kids.min()) if len(kids) else 0
        hi = int(kids.max()) + 1 if len(kids) else 0
        if lo >= base and hi <= base + len(arr):
            return arr[kids - base].astype(_I64)
        out = np.full(len(kids), -1, dtype=_I64)
        m = (kids >= base) & (kids < base + len(arr))
        out[m] = arr[kids[m] - base]
        return out

    def cnt_rows_assign(self, rank: int, kids: np.ndarray,
                        rows: np.ndarray) -> None:
        """Record kid -> row for freshly created slots (kids unique).
        Picks the representation: the dense window grows to cover the new
        kids unless that leaves it < 1/CNT_WINDOW_MIN_FILL occupied past
        the dense floor — then the rank converts to hash mode."""
        live = self.cnt_rank_live.get(rank, 0) + len(kids)
        self.cnt_rank_live[rank] = live
        h = self.cnt_rank_hash.get(rank)
        if h is None:
            lo, hi = int(kids.min()), int(kids.max()) + 1
            ent = self.cnt_rank_rows.get(rank)
            _, cap = self._window_cap(lo, hi, ent)
            if cap <= self.CNT_WINDOW_DENSE_FLOOR or \
                    live * self.CNT_WINDOW_MIN_FILL >= cap:
                base, arr = self.cnt_rank_rows_arr(rank, lo, hi)
                arr[kids - base] = rows.astype(np.int32)
                return
            h = self._rank_to_hash(rank)
        h.put_batch(kids, rows)

    def _cnt_row(self, kid: int, node: int) -> int:
        """Existing or fresh (both pairs unwritten) slot row."""
        rank = self.rank_of(node)
        h = self.cnt_rank_hash.get(rank)
        if h is not None:
            row = h.get(kid, -1)
            if row < 0:
                row = self.cnt.append(kid=kid, node=node, val=0,
                                      uuid=self.NEUTRAL_T,
                                      base=0, base_t=self.NEUTRAL_T)
                h.put(kid, row)
                self.cnt_rank_live[rank] = \
                    self.cnt_rank_live.get(rank, 0) + 1
            return row
        ent = self.cnt_rank_rows.get(rank)
        _, cap = self._window_cap(kid, kid + 1, ent)
        if cap > self.CNT_WINDOW_DENSE_FLOOR and \
                (self.cnt_rank_live.get(rank, 0) + 1) * \
                self.CNT_WINDOW_MIN_FILL < cap:
            self._rank_to_hash(rank)
            return self._cnt_row(kid, node)
        base, arr = self.cnt_rank_rows_arr(rank, kid, kid + 1)
        row = int(arr[kid - base])
        if row < 0:
            row = self.cnt.append(kid=kid, node=node, val=0, uuid=self.NEUTRAL_T,
                                  base=0, base_t=self.NEUTRAL_T)
            arr[kid - base] = row
            self.cnt_rank_live[rank] = self.cnt_rank_live.get(rank, 0) + 1
        return row

    def counter_slot_total(self, kid: int, node: int) -> int:
        """Read-only probe of one (key, node) slot's lifetime total (0 for
        an unwritten slot).  The serve coalescer plans INCR rewrites from
        this without materializing the slot row (`_cnt_row` would) — the
        planned CNTSET batch row creates it when the run lands."""
        rank = self.rank_of(node)
        h = self.cnt_rank_hash.get(rank)
        if h is not None:
            row = h.get(kid, -1)
        else:
            row = -1
            ent = self.cnt_rank_rows.get(rank)
            if ent is not None:
                base, arr = ent
                if base <= kid < base + len(arr):
                    row = int(arr[kid - base])
        return int(self.cnt.val[row]) if row >= 0 else 0

    def _sync_cnt_lists(self) -> None:
        n = self.cnt.n
        if self._cnt_synced < n:
            by_kid = self.cnt_rows_by_kid
            for off, kid in enumerate(self.cnt.kid[self._cnt_synced:n].tolist()):
                by_kid.setdefault(kid, []).append(self._cnt_synced + off)
            self._cnt_synced = n

    def counter_change(self, kid: int, node: int, delta: int, uuid: int) -> tuple[int, int]:
        """Local INCR/DECR on the caller's own slot: the cumulative lifetime
        total advances by `delta` at `uuid`.  -> (new visible sum, new total).

        Counter model (diverges deliberately from the reference's delta
        scheme, type_counter.rs + cmd.rs:233-254, which requires exactly-once
        in-order delivery and still diverges around deletes): a slot is a
        single-writer LWW register holding the writer's lifetime total, plus
        a delete-observed `base` LWW register; the visible contribution is
        total - base.  Every component is an LWW assignment, so replication
        is idempotent, reorder-safe, and bit-identical to state merges.
        """
        row = self._cnt_row(kid, node)
        if uuid > int(self.cnt.uuid[row]):
            self.cnt.val[row] += delta
            self.cnt.uuid[row] = uuid
            self.keys.cnt_sum[kid] += delta
        return int(self.keys.cnt_sum[kid]), int(self.cnt.val[row])

    def counter_set_total(self, kid: int, node: int, total: int, uuid: int) -> None:
        """Replicated total assignment (CNTSET): LWW on uuid."""
        row = self._cnt_row(kid, node)
        if uuid > int(self.cnt.uuid[row]):
            self.keys.cnt_sum[kid] += total - int(self.cnt.val[row])
            self.cnt.val[row] = total
            self.cnt.uuid[row] = uuid

    def counter_set_base(self, kid: int, node: int, base: int, base_t: int) -> None:
        """Delete-observed base assignment (DELCNT): LWW on delete time,
        max-base on exact ties (concurrent deletes on different nodes can
        mint the same uuid — must mirror merge_counter_slot's tie rule)."""
        row = self._cnt_row(kid, node)
        b0, bt0 = int(self.cnt.base[row]), int(self.cnt.base_t[row])
        if base_t > bt0 or (base_t == bt0 and base > b0):
            self.keys.cnt_sum[kid] -= base - b0
            self.cnt.base[row] = base
            self.cnt.base_t[row] = base_t

    def counter_sum(self, kid: int) -> int:
        return int(self.keys.cnt_sum[kid])

    def counter_slots(self, kid: int) -> list[tuple[int, int, int, int, int]]:
        """[(node, total, uuid, base, base_t)] for DESC / DEL / snapshot."""
        self._sync_cnt_lists()
        out = []
        for row in self.cnt_rows_by_kid.get(kid, ()):
            out.append((int(self.cnt.node[row]), int(self.cnt.val[row]),
                        int(self.cnt.uuid[row]), int(self.cnt.base[row]),
                        int(self.cnt.base_t[row])))
        return out

    def recompute_counter_sums(self) -> None:
        """Vectorized re-derivation of every key's sum cache (used by the
        batched engines after bulk slot merges)."""
        n = self.cnt.n
        nk = self.keys.n
        if not n:
            self.keys.cnt_sum[:nk] = 0
            return
        contrib = self.cnt.val[:n] - self.cnt.base[:n]
        kid = self.cnt.kid[:n]
        amax = int(np.abs(contrib).max())
        # bincount accumulates in float64 — exact only while every partial
        # sum stays under 2^53, guaranteed by n * max|contrib| < 2^53;
        # larger magnitudes fall back to the (slower) exact int64 add.at
        if amax and n * amax < (1 << 53):
            sums = np.bincount(kid, weights=contrib, minlength=nk)
            self.keys.cnt_sum[:nk] = sums[:nk].astype(_I64)
        elif amax == 0:
            self.keys.cnt_sum[:nk] = 0
        else:
            sums = np.zeros(nk, dtype=_I64)
            np.add.at(sums, kid, contrib)
            self.keys.cnt_sum[:nk] = sums

    def counter_merge_slot(self, kid: int, node: int, total: int, uuid: int,
                           base: int, base_t: int) -> None:
        """State-merge of one foreign slot (CPU merge engine): both LWW
        pairs merge independently (max-total on exact uuid ties)."""
        row = self._cnt_row(kid, node)
        v0, t0 = int(self.cnt.val[row]), int(self.cnt.uuid[row])
        v1, t1 = S.merge_counter_slot(v0, t0, total, uuid)
        if (v1, t1) != (v0, t0):
            self.keys.cnt_sum[kid] += v1 - v0
            self.cnt.val[row], self.cnt.uuid[row] = v1, t1
        b0, bt0 = int(self.cnt.base[row]), int(self.cnt.base_t[row])
        b1, bt1 = S.merge_counter_slot(b0, bt0, base, base_t)
        if (b1, bt1) != (b0, bt0):
            self.keys.cnt_sum[kid] -= b1 - b0
            self.cnt.base[row], self.cnt.base_t[row] = b1, bt1

    # ------------------------------------------------------------- registers

    def register_set(self, kid: int, val: bytes, uuid: int, node: int) -> bool:
        """Op-level LWW write (client SET / replicated SET)."""
        if S.lww_wins(int(self.keys.rv_t[kid]), int(self.keys.rv_node[kid]), uuid, node):
            return False
        self.reg_val[kid] = val
        self.keys.rv_t[kid], self.keys.rv_node[kid] = uuid, node
        self.updated_at(kid, uuid)
        return True

    def register_get(self, kid: int) -> Optional[bytes]:
        return self.reg_val[kid]

    def register_state(self, kid: int) -> tuple[Optional[bytes], int, int]:
        return self.reg_val[kid], int(self.keys.rv_t[kid]), int(self.keys.rv_node[kid])

    def register_merge(self, kid: int, val: bytes, t: int, node: int) -> None:
        if S.lww_wins(t, node, int(self.keys.rv_t[kid]), int(self.keys.rv_node[kid])):
            self.reg_val[kid] = val
            self.keys.rv_t[kid], self.keys.rv_node[kid] = t, node

    # -------------------------------------------------------------- elements

    def el_combo(self, kid: int, member: bytes) -> int:
        """Stable combo id for an element slot; interns the member bytes."""
        mid = self.member_index.get_or_insert(member)
        return (kid << self.MEMBER_BITS) | mid

    def el_row(self, kid: int, member: bytes) -> int:
        mid = self.member_index.lookup(member)
        if mid < 0:
            return -1
        return self.el_index.get((kid << self.MEMBER_BITS) | mid, -1)

    def elem_add(self, kid: int, member: bytes, val: Optional[bytes],
                 uuid: int, node: int) -> bool:
        """SADD member / HSET field: pure pointwise add-side LWW write, so
        the op path and the state-merge path (elem_merge) compute the same
        function.  (The reference instead DROPS adds older than the del time
        or the stored add time — lwwhash.rs:87-107 — which leaves replicas
        that saw different op interleavings with different hidden state.)
        Returns True iff the member became visible by this op."""
        combo = self.el_combo(kid, member)
        row = self.el_index.get(combo, -1)
        if row < 0:
            self._el_new_row(combo, kid, member, val, uuid, node)
            return True  # del_t == 0 → visible
        at, an = int(self.el.add_t[row]), int(self.el.add_node[row])
        dt = int(self.el.del_t[row])
        was_alive = S.elem_alive(at, dt)
        if not S.lww_wins(at, an, uuid, node):
            self.el.add_t[row], self.el.add_node[row] = uuid, node
            self.el_val[row] = val
            at = uuid
        return S.elem_alive(at, dt) and not was_alive

    def elem_rem(self, kid: int, member: bytes, uuid: int) -> bool:
        """SREM member / HDEL field: pure pointwise del-side max (see
        elem_add; reference lwwhash.rs:109-128 drops dels older than the
        stored add time).  Returns True iff the member became invisible."""
        combo = self.el_combo(kid, member)
        row = self.el_index.get(combo, -1)
        if row < 0:
            # record the tombstone, but an absent member was not "removed"
            row = self._el_new_row(combo, kid, member, None, 0, 0)
            self.el.del_t[row] = uuid
            self._enqueue_garbage(uuid, self.key_bytes[kid], member)
            return False
        at, dt = int(self.el.add_t[row]), int(self.el.del_t[row])
        was_alive = S.elem_alive(at, dt)
        if uuid > dt:
            self.el.del_t[row] = dt = uuid
            if at < dt:
                self._enqueue_garbage(dt, self.key_bytes[kid], member)
        return was_alive and not S.elem_alive(at, dt)

    def elem_get(self, kid: int, member: bytes) -> Optional[bytes]:
        """Live dict-field value or None."""
        row = self.el_row(kid, member)
        if row < 0:
            return None
        if S.elem_alive(int(self.el.add_t[row]), int(self.el.del_t[row])):
            return self.el_val[row]
        return None

    def _sync_el_lists(self) -> None:
        n = self.el.n
        if self._el_synced < n:
            by_kid = self.el_rows_by_kid
            for off, kid in enumerate(self.el.kid[self._el_synced:n].tolist()):
                by_kid.setdefault(kid, []).append(self._el_synced + off)
            self._el_synced = n

    def _live_rows(self, kid: int) -> Iterator[int]:
        self._sync_el_lists()
        for row in self.el_rows_by_kid.get(kid, ()):
            if int(self.el.kid[row]) == kid:
                yield row

    def elem_live(self, kid: int) -> Iterator[tuple[bytes, Optional[bytes], int]]:
        """(member, value, add_t) for visible elements."""
        for row in self._live_rows(kid):
            if S.elem_alive(int(self.el.add_t[row]), int(self.el.del_t[row])):
                yield self.el_member[row], self.el_val[row], int(self.el.add_t[row])

    def elem_all(self, kid: int) -> Iterator[tuple[bytes, int, int, int, Optional[bytes]]]:
        """(member, add_t, add_node, del_t, value) incl. tombstones."""
        for row in self._live_rows(kid):
            yield (self.el_member[row], int(self.el.add_t[row]),
                   int(self.el.add_node[row]), int(self.el.del_t[row]),
                   self.el_val[row])

    # ------------------------------------------------- batched read gathers
    # The serve coalescer's read planner (server/serve.py) resolves a
    # whole pipelined read run against the columns in a handful of
    # vectorized passes instead of per-command (and per-member) hash
    # probes + scalar reads.  Each gather is the exact batch twin of the
    # single-op read above it — same row order, same liveness rule — so
    # planned replies are byte-identical to the per-command path's.

    def register_get_batch(self, kids) -> list:
        """Register blobs for a batch of kids (`register_get` twin)."""
        reg = self.reg_val
        return [reg[kid] for kid in kids]

    def counter_sum_batch(self, kid_arr: np.ndarray) -> list[int]:
        """Visible counter totals for a batch of kids in one gather off
        the incrementally-maintained sum column (`counter_sum` twin —
        the slot/bincount machinery keeps `cnt_sum` exact through every
        merge path)."""
        if len(kid_arr) < 8:  # below the fancy-index floor
            col = self.keys.cnt_sum
            return [int(col[kid]) for kid in kid_arr]
        return self.keys.cnt_sum[kid_arr].tolist()

    def elem_live_rows_batch(self, kids) -> list[np.ndarray]:
        """Live element rows per kid, in row (append) order — the batch
        twin of iterating `elem_live`: one concatenated mask over
        `add_t >= del_t` plus the compaction-staleness kid check
        replaces per-row scalar reads."""
        self._sync_el_lists()
        by_kid = self.el_rows_by_kid
        per = [by_kid.get(kid, ()) for kid in kids]
        counts = [len(p) for p in per]
        total = sum(counts)
        if not total:
            return [np.empty(0, dtype=_I64) for _ in kids]
        if total < 64:
            # below the vectorization floor the array setup costs more
            # than the scalar walk it replaces (a fragmented read run
            # gathers a couple of small sets per batch)
            el_kid, add_t, del_t = self.el.kid, self.el.add_t, self.el.del_t
            return [np.fromiter(
                (r for r in p
                 if el_kid[r] == kid and add_t[r] >= del_t[r]),
                dtype=_I64) for kid, p in zip(kids, per)]
        rows = np.empty(total, dtype=_I64)
        pos = 0
        for p, c in zip(per, counts):
            if c:
                rows[pos:pos + c] = p
                pos += c
        el = self.el
        owner = np.repeat(np.asarray(kids, dtype=_I64),
                          np.asarray(counts, dtype=_I64))
        live = (el.kid[rows] == owner) & (el.add_t[rows] >= el.del_t[rows])
        out = []
        pos = 0
        for c in counts:
            sl = rows[pos:pos + c]
            out.append(sl[live[pos:pos + c]])
            pos += c
        return out

    def elem_probe_batch(self, kid_arr: np.ndarray,
                         members: list) -> tuple[np.ndarray, np.ndarray]:
        """(row, alive) per (kid, member) pair — the batch twin of
        `el_row` + `elem_alive` (HGET / SISMEMBER probes): one member
        interner batch + one combo-index batch replaces two hash probes
        per command.  Rows are -1 for unknown members/combos."""
        n = len(members)
        if n < 8:
            # scalar twin below the vectorization floor (same liveness
            # rule, no array setup)
            rows = np.full(n, -1, dtype=_I64)
            alive = np.zeros(n, dtype=bool)
            el = self.el
            for x in range(n):
                row = self.el_row(int(kid_arr[x]), members[x])
                if row >= 0:
                    rows[x] = row
                    alive[x] = el.add_t[row] >= el.del_t[row]
            return rows, alive
        mids = self.member_index.lookup_batch(members)
        combos = (kid_arr << self.MEMBER_BITS) | mids
        rows = self.el_index.lookup_batch(combos)
        rows[mids < 0] = -1
        alive = np.zeros(len(rows), dtype=bool)
        hit = rows >= 0
        if hit.any():
            hr = rows[hit]
            alive[hit] = self.el.add_t[hr] >= self.el.del_t[hr]
        return rows, alive

    def elem_merge(self, kid: int, member: bytes, add_t: int, add_node: int,
                   del_t: int, val: Optional[bytes]) -> None:
        """State-merge of one foreign element (CPU merge engine)."""
        combo = self.el_combo(kid, member)
        row = self.el_index.get(combo, -1)
        if row < 0:
            row = self._el_new_row(combo, kid, member, val, add_t, add_node)
            self.el.del_t[row] = del_t
            if add_t < del_t:
                self._enqueue_garbage(del_t, self.key_bytes[kid], member)
            return

        a0, n0, d0 = int(self.el.add_t[row]), int(self.el.add_node[row]), int(self.el.del_t[row])
        at, an, dt, local_wins = S.merge_elem(a0, n0, d0, add_t, add_node, del_t)
        self.el.add_t[row], self.el.add_node[row], self.el.del_t[row] = at, an, dt
        if not local_wins:
            self.el_val[row] = val
        # re-queue whenever the merged row is dead and its del_t advanced (a
        # pending entry at the old, smaller del_t would be discarded by gc)
        if at < dt and dt > d0:
            self._enqueue_garbage(dt, self.key_bytes[kid], member)

    def _el_new_row(self, combo: int, kid: int, member: bytes,
                    val: Optional[bytes], add_t: int, add_node: int) -> int:
        row = self.el.append(kid=kid, add_t=add_t, add_node=add_node, del_t=0)
        self.el_member.append(member)
        self.el_val.append(val)
        self.el_index.put(combo, row)
        return row

    # -------------------------------------------------------------- tensors
    # The two-layer tensor register (crdt/tensor.py): per-(key, node)
    # contributor slots merge as LWW on uuid (the payload and count ride
    # the winner — exactly the counter-slot rule with an object payload),
    # and reads reduce the live contributor set with the key's registered
    # strategy in canonical (node, uuid) order.  `tensor_merge_row` is
    # the ONE per-row reference implementation: the op path, the CPU
    # engine, and the host micro strategy all call it; the device micro
    # path (engine/tpu.py) folds + scatters the very same decisions in
    # batch and is differential-tested byte-identical.

    def tensor_get_or_create(self, key: bytes, cfg: bytes,
                             uuid: int) -> int:
        """Existing tensor key (enc- and config-checked) or a fresh one
        whose config is fixed from `cfg` (packed TensorMeta)."""
        kid, _created = self.get_or_create(key, S.ENC_TENSOR, uuid)
        meta = self.tns_meta.get(kid)
        if meta is None:
            self.tns_meta[kid] = T.unpack_config(cfg)
        elif T.pack_config(meta) != bytes(cfg):
            raise T.TensorConfigError(
                "tensor config mismatch: shape/dtype/strategy are fixed "
                "at key creation")
        return kid

    def tensor_meta_of(self, kid: int) -> Optional[T.TensorMeta]:
        return self.tns_meta.get(kid)

    def tensor_slot_row(self, kid: int, node: int) -> int:
        """Existing or fresh (neutral) contributor slot row."""
        combo = (kid << self.NODE_RANK_BITS) | self.rank_of(node)
        row = self.tns_index.get(combo, -1)
        if row < 0:
            row = self.tns.append(kid=kid, node=node, uuid=self.NEUTRAL_T,
                                  cnt=0)
            self.tns_payload.append(None)
            self.tns_index.put(combo, row)
        return row

    def tensor_assign_payload(self, row: int, arr: np.ndarray) -> None:
        """Replace a slot's payload array, keeping the byte gauge exact
        (the device flush path writes downloaded rows through here)."""
        old = self.tns_payload[row]
        if old is not None:
            self.tns_bytes -= old.nbytes
        self.tns_payload[row] = arr
        self.tns_bytes += arr.nbytes

    def tensor_slot_set(self, kid: int, node: int, uuid: int, cnt: int,
                        payload: np.ndarray) -> bool:
        """LWW-assign one contributor slot (op path == merge path; the
        strict > keeps equal-uuid re-delivery idempotent — one node's
        uuids are unique per write, so an equal stamp IS the same
        write).  `payload` must already be the meta-normalized array."""
        row = self.tensor_slot_row(kid, node)
        if uuid <= int(self.tns.uuid[row]):
            return False
        self.tns.uuid[row] = uuid
        self.tns.cnt[row] = cnt
        self.tensor_assign_payload(row, payload)
        return True

    def tensor_count_merge(self, meta: T.TensorMeta, n: int = 1) -> None:
        """Bump the per-strategy merge gauge (INFO).  Counted once per
        VALIDATED delivered contribution — not per LWW win — so the
        gauge reads the same whichever engine or routing processed the
        rows (the device path folds intra-batch duplicates before its
        win test, a per-win count would depend on routing)."""
        name = meta.strat_name
        self.tns_merges_by_strat[name] = \
            self.tns_merges_by_strat.get(name, 0) + n

    def tensor_merge_row(self, kid: int, node: int, uuid: int, cnt: int,
                         cfg: bytes, payload) -> bool:
        """State-merge one foreign contributor row (the per-row
        reference both engines' batch paths must match).  Config
        mismatches and malformed payloads are skipped with a log —
        snapshot-merge semantics, like type conflicts."""
        meta = self.tns_meta.get(kid)
        try:
            T.check_count(cnt)
            if meta is None:
                meta = T.unpack_config(cfg)
                self.tns_meta[kid] = meta
            elif T.pack_config(meta) != bytes(cfg):
                raise T.TensorConfigError("tensor config mismatch")
            arr = T.payload_array(meta, payload)
        except T.TensorConfigError as e:
            import logging
            logging.getLogger(__name__).error(
                "skipping tensor row for kid %d: %s", kid, e)
            return False
        self.tensor_count_merge(meta)
        return self.tensor_slot_set(kid, node, uuid, cnt, arr)

    def _sync_tns_lists(self) -> None:
        n = self.tns.n
        if self._tns_synced < n:
            by_kid = self.tns_rows_by_kid
            for off, kid in enumerate(
                    self.tns.kid[self._tns_synced:n].tolist()):
                by_kid.setdefault(kid, []).append(self._tns_synced + off)
            self._tns_synced = n

    def tensor_contrib_rows(self, kid: int) -> list[int]:
        """Slot rows of one key holding a real write, in canonical
        (node, uuid) ascending order — THE reduction order every
        strategy uses (crdt/tensor.py canonical_order)."""
        self._sync_tns_lists()
        # membership comes from the STAMP column alone (host-
        # authoritative): under a resident engine a merged slot's host
        # payload stays stale until flush, but the slot is already a
        # contributor — the device read serves its payload from the pool
        rows = [r for r in self.tns_rows_by_kid.get(kid, ())
                if int(self.tns.uuid[r]) != self.NEUTRAL_T]
        rows.sort(key=lambda r: (int(self.tns.node[r]),
                                 int(self.tns.uuid[r])))
        return rows

    def tensor_contribs(self, kid: int) -> list[tuple]:
        """[(node, uuid, cnt, payload)] in canonical order (STAT /
        snapshot / canonical)."""
        return [(int(self.tns.node[r]), int(self.tns.uuid[r]),
                 int(self.tns.cnt[r]), self.tns_payload[r])
                for r in self.tensor_contrib_rows(kid)]

    def tensor_read(self, kid: int) -> Optional[np.ndarray]:
        """Host reference read: the key's strategy reduced over the
        contributor set in canonical order (flat [elems] array; callers
        reshape via the meta).  None when no contribution landed yet."""
        meta = self.tns_meta.get(kid)
        rows = self.tensor_contrib_rows(kid)
        if meta is None or not rows:
            return None
        mat = np.stack([self.tns_payload[r] for r in rows])
        return T.reduce_rows(meta.strat, mat, self.tns.cnt[rows],
                             self.tns.uuid[rows], self.tns.node[rows])

    # ------------------------------------------------------------------- GC

    def gc(self, horizon: int) -> int:
        """Physically drop tombstones every replica has acknowledged
        (parity: reference db.rs:82-119, fixed to pop oldest-first and to
        actually collect equal-time entries)."""
        freed = 0
        el_freed = 0
        while self.garbage:
            t, _seq, key, member = self.garbage[0]
            if t > horizon:
                break
            heapq.heappop(self.garbage)
            if member is None:
                if self.key_deletes.get(key) == t:
                    del self.key_deletes[key]
                    freed += 1
                continue
            kid = self.key_index.lookup(key)
            if kid < 0:
                continue
            row = self.el_row(kid, member)
            if row < 0:
                continue
            at, dt = int(self.el.add_t[row]), int(self.el.del_t[row])
            if at < dt and dt <= horizon:
                mid = self.member_index.lookup(member)
                self.el_index.delete((kid << self.MEMBER_BITS) | mid)
                self.el.kid[row] = -1
                self.el_member[row] = None
                self.el_val[row] = None
                self.el_dead += 1
                freed += 1
                el_freed += 1
        if el_freed:
            # a resident engine's device mirrors gather/scatter by row id;
            # any element-row removal (and especially the compaction below,
            # which REORDERS rows) must invalidate them or later flushes
            # write stale columns over the collected table.  key_deletes-only
            # rounds touch no mirrored column and skip the bump.
            self.touch("el")
        if self.el_dead > 10_000 and self.el_dead * 2 > self.el.n:
            self._compact_elements()
        return freed

    def _compact_elements(self) -> None:
        """Rebuild element storage without dead rows (replaces free-list
        reuse: row ids must stay stable BETWEEN compactions so the batched
        engine's staged row indices never alias)."""
        self.touch("el")  # row ids change: resident device mirrors are stale
        # row ids are about to change: the digest's member-crc cache is
        # row-aligned and must rebuild from the compacted columns.  The
        # lock orders this against an off-loop warm_digest_caches pass:
        # either the warm stored its cache first (we drop it here) or it
        # observes the epoch bump and drops its own pass — never a
        # misaligned cache surviving.  Worst case this waits out one
        # in-flight warm (gc-triggered compaction, background path).
        with self._crc_lock:
            self.el_compact_epoch += 1
            self._member_crc = None
            self._member_crc_n = 0
        n = self.el.n
        live = np.nonzero(self.el.kid[:n] >= 0)[0]
        # row-id stability accounting: rows only die through gc() (which
        # counts el_dead) and only compaction re-identifies them, so the
        # dead-row census must match exactly.  A mismatch means some path
        # reused or dropped a row id between compactions — the batched
        # engine's staged row indices would silently alias.  Real raise,
        # not assert: `python -O` must not strip this guard.
        if n - len(live) != self.el_dead:
            raise RuntimeError(
                f"element row-id stability broken: {n - len(live)} dead "
                f"rows found but {self.el_dead} accounted")
        new_el = _ElCols()
        new_el.append_block(len(live), kid=self.el.kid[live],
                            add_t=self.el.add_t[live],
                            add_node=self.el.add_node[live],
                            del_t=self.el.del_t[live])
        # rebinding the blob planes bypasses BlobList accounting: retire
        # the old lists' bytes, and the fresh BlobLists re-add their own
        # (net zero — gc() already nulled every dead row's blobs)
        self.blob_bytes -= sum(map(_blen, self.el_member)) + \
            sum(map(_blen, self.el_val))
        members = BlobList(self, (self.el_member[r] for r in live.tolist()))
        self.el_val = BlobList(self, (self.el_val[r] for r in live.tolist()))
        self.el_member = members
        self.el = new_el
        self.el_dead = 0
        # rebuild combo index + per-kid lists with the new row ids
        self.el_index = I64Dict(max(len(live), 16))
        by_kid: dict[int, list[int]] = {}
        kids = new_el.kid[: new_el.n].tolist()
        if members:
            mids, _ = self.member_index.get_or_insert_batch(members)
            combos = (np.asarray(kids, dtype=_I64) << self.MEMBER_BITS) | mids
            self.el_index.put_batch(combos, np.arange(len(live), dtype=_I64))
        for row, kid in enumerate(kids):
            by_kid.setdefault(kid, []).append(row)
        self.el_rows_by_kid = by_kid
        self._el_synced = new_el.n

    # ------------------------------------------------------------ inspection

    def canonical(self, keys=None) -> dict:
        """Full logical state (incl. tombstones) for convergence checks.
        `keys`: restrict to these key bytes (absent keys are omitted — a
        comparison against an oracle that HAS them then fails loudly);
        used by bench.py to oracle-verify a subsample of a 10M-key store
        without walking all of it."""
        out = {}
        if keys is not None:
            items = ((self.lookup(k), k) for k in keys)
            items = ((kid, k) for kid, k in items if kid >= 0)
        else:
            items = enumerate(self.key_bytes)
        for kid, key in items:
            enc = int(self.keys.enc[kid])
            ct, mt, dt = self.envelope(kid)
            if enc == S.ENC_COUNTER:
                content = frozenset(self.counter_slots(kid))
            elif enc == S.ENC_BYTES:
                content = self.register_state(kid)
            elif enc == S.ENC_TENSOR:
                meta = self.tns_meta.get(kid)
                cfg = T.pack_config(meta) if meta is not None else b""
                content = (cfg, frozenset(
                    (node, uuid, cnt, p.tobytes())
                    for node, uuid, cnt, p in self.tensor_contribs(kid)))
            else:
                # a del_t at or below add_t is semantically inert (visibility
                # and every future max-merge are unchanged by zeroing it), and
                # GC timing legitimately leaves different inert values on
                # different replicas — normalize so canonical state converges
                content = frozenset(
                    (m, at, an, dlt if dlt > at else 0, v)
                    for m, at, an, dlt, v in self.elem_all(kid)
                )
            out[key] = (enc, ct, mt, dt, int(self.keys.expire[kid]), content)
        return out

    def describe(self, kid: int) -> dict:
        """DESC command payload: raw CRDT state incl. tombstones."""
        enc = int(self.keys.enc[kid])
        ct, mt, dt = self.envelope(kid)
        d = {"enc": S.ENC_NAMES.get(enc, str(enc)), "ct": ct, "mt": mt, "dt": dt}
        if enc == S.ENC_COUNTER:
            d["slots"] = sorted(self.counter_slots(kid))
            d["sum"] = self.counter_sum(kid)
        elif enc == S.ENC_TENSOR:
            meta = self.tns_meta.get(kid)
            if meta is not None:
                d["strategy"] = meta.strat_name
                d["dtype"] = T.DTYPE_NAMES[meta.dtype_code]
                d["shape"] = meta.shape
            d["contributors"] = [(n_, u, c)
                                 for n_, u, c, _p in
                                 self.tensor_contribs(kid)]
        elif enc == S.ENC_BYTES:
            val, t, node = self.register_state(kid)
            d["value"], d["vtime"], d["vnode"] = val, t, node
        else:
            d["elems"] = sorted(self.elem_all(kid))
        return d

    def used_bytes(self) -> int:
        """The store's governed memory footprint (server/overload.py):
        LIVE numeric rows + the incrementally-tracked blob and tensor
        payload bytes.  Deliberately excludes index-table overhead and
        pow2 column slack so shards=N sums to exactly the shards=1
        figure (the accounting-invariance property test pins this) —
        the watermarks are set against this gauge, so what matters is
        that it tracks growth exactly, not that it equals RSS."""
        return (self.keys.live_bytes() + self.cnt.live_bytes()
                + self.el.live_bytes() + self.tns.live_bytes()
                + self.blob_bytes + self.tns_bytes)

    def release_warm_caches(self) -> None:
        """Drop rebuildable warm-path caches (the hard-watermark
        degradation step, server/overload.py): the incremental digest
        crc caches — the next digest exchange re-fills them lazily, at
        the documented off-loop-warm cost.  Taken under the crc lock so
        an in-flight off-loop warm can never store a freed cache back."""
        with self._crc_lock:
            self._key_crc = None
            self._key_crc_n = 0
            self._member_crc = None
            self._member_crc_n = 0

    def memory_report(self) -> dict:
        """Store memory accounting for INFO: exact numeric-plane bytes
        (column capacities) plus row/byte-string counts (the blob planes
        are Python bytes objects; counting them exactly would walk O(rows)
        objects, so INFO reports counts and lets RSS cover the rest —
        reference src/lib.rs:63-78 leans on jemalloc the same way)."""
        return {
            "used_bytes": self.used_bytes(),
            "blob_bytes": self.blob_bytes,
            "numeric_bytes": (self.keys.nbytes() + self.cnt.nbytes()
                              + self.el.nbytes() + self.tns.nbytes()
                              + sum(a.nbytes for _, a
                                    in self.cnt_rank_rows.values())
                              # hash-mode ranks: ~16B/entry estimate
                              + sum(16 * len(h)
                                    for h in self.cnt_rank_hash.values())),
            "keys": self.keys.n,
            "counter_slots": self.cnt.n,
            "element_rows": self.el.n,
            "element_rows_dead": self.el_dead,
            "tensor_slots": self.tns.n,
            "tensor_payload_bytes": self.tns_bytes,
            "interned_members": len(self.member_index),
            "key_tombstones": len(self.key_deletes),
            "garbage_queue": len(self.garbage),
        }
