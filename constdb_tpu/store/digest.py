"""Anti-entropy state digests: order-independent folds over the crc32
key partition (the delta-sync negotiation in replica/link.py).

A digest bucket's value is a PURE FUNCTION of the logical CRDT state of
the keys it owns — any two stores holding the same logical state produce
the same matrix, whatever engine merged it, however its shards are laid
out, and in whatever order the ops arrived.  That is the whole load:
pusher and puller exchange matrices, and only buckets whose folds differ
are streamed (docs/INVARIANTS.md "Digest anti-entropy").

Geometry: a key lands in shard `crc32(key) % fanout` (the SAME crc32
partition store/sharded_keyspace.py shards by, so a sharded node's
workers each digest their disjoint key set and the parent SUMS the
matrices) and leaf `(crc32(key) // fanout) % leaves`.  The level-0
rollup a pusher compares first is the per-shard sum over leaves — which
equals the `leaves=1` matrix, so the two levels never need to agree on
a leaf count up front.

Per-key content, all folded as unordered mod-2^64 sums of mixed 64-bit
hashes (sum ⇒ shard layout and row order are invisible):

  * envelope row:  crc32(key), enc, ct, mt, dt, expire, rv_t, rv_node.
    The register VALUE bytes are deliberately absent: an LWW register's
    (rv_t, rv_node) pair identifies the winning write, and one write has
    one value — hashing the pair is hashing the value, without an
    O(keys) Python pass over the blobs.
  * counter slot:  crc32(key), node, val, uuid, base, base_t (same
    writer-identifies-value argument would allow dropping val/base, but
    they are numeric columns — hashing them is free and belt-and-braces).
  * element row:   crc32(key), crc32(member), add_t, add_node, and
    del_t NORMALIZED to 0 when <= add_t — the same inert-tombstone rule
    KeySpace.canonical applies, so GC-timing skew between replicas does
    not flag spurious divergence.  GC-dead rows (kid < 0) are excluded.
    Element VALUES ride on (add_t, add_node), like register values.
  * key tombstone: crc32(key), delete time — the `key_deletes` record,
    which is the only trace of a delete merged for a never-seen key.

Cost model (the "incremental digest" law): the per-item Python work —
crc32 of key and member bytes — is cached on the store and maintained
incrementally in append order (KeySpace.key_crcs / member_crcs; element
compaction invalidates the member cache).  The numeric folds are a
vectorized numpy pass over the live columns at exchange time: O(state)
at memory bandwidth, run once per digest request on a path whose
alternative was shipping the whole keyspace over the wire.
"""

from __future__ import annotations

import zlib

import numpy as np

from ..engine.base import ColumnarBatch, batch_from_keyspace
from .keyspace import KeySpace

_U64 = np.uint64
_GOLD = np.uint64(0x9E3779B97F4A7C15)
_MUL1 = np.uint64(0xBF58476D1CE4E5B9)
_MUL2 = np.uint64(0x94D049BB133111EB)

# per-plane seeds: a counter slot and an element row with coincidentally
# equal numeric columns must not cancel across planes
_SEED_ENV = np.uint64(0x1B873593A5A5A5A5)
_SEED_CNT = np.uint64(0x2545F4914F6CDD1D)
_SEED_EL = np.uint64(0x632BE59BD9B4E019)
_SEED_DEL = np.uint64(0x9E6C63D0876A9A47)
_SEED_TNS = np.uint64(0x7FEB352D243F6A88)

# the negotiated shard axis: the SAME crc32 partition
# store/sharded_keyspace.py shards by, at its maximum width, so any
# node's physical shard layout (1..64 workers) nests inside it and a
# digest request never depends on either side's worker count
DIGEST_FANOUT = 64

# largest matrix a peer may request (replica/link.py bounds requests to
# this before allocating): 2^22 buckets = 32 MB of uint64
MAX_BUCKETS = 1 << 22


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer, vectorized (uint64 wraparound)."""
    x = x ^ (x >> np.uint64(30))
    x = x * _MUL1
    x = x ^ (x >> np.uint64(27))
    x = x * _MUL2
    return x ^ (x >> np.uint64(31))

def _chain(seed: np.uint64, *cols) -> np.ndarray:
    """Positional hash chain over aligned columns (order matters inside
    a row; rows themselves are folded unordered by the caller)."""
    h = None
    for c in cols:
        c = np.asarray(c).astype(_U64, copy=False)
        if h is None:
            h = _mix64(c + seed)
        else:
            h = _mix64((h * _MUL1) ^ c)
    return h


def leaves_for(n_keys: int, fanout: int, bucket_keys: int) -> int:
    """Leaf count targeting ~`bucket_keys` keys per (shard, leaf) bucket
    (pow2-rounded).  Fine buckets are what turn 1% key divergence into
    ~1% of buckets streamed instead of 100% of shards."""
    want = max(1, n_keys // max(1, fanout * max(1, bucket_keys)))
    leaves = 1
    while leaves < want and leaves * fanout < MAX_BUCKETS:
        leaves <<= 1
    return leaves


def _buckets(crc: np.ndarray, fanout: int, leaves: int) -> np.ndarray:
    shard = crc % np.uint64(fanout)
    leaf = (crc // np.uint64(fanout)) % np.uint64(leaves)
    return (shard * np.uint64(leaves) + leaf).astype(np.int64)


def _env_hashes(ks: KeySpace, kcrc: np.ndarray) -> np.ndarray:
    """One hash per key envelope row, kid-aligned."""
    return _chain(_SEED_ENV, kcrc, ks.keys.enc, ks.keys.ct, ks.keys.mt,
                  ks.keys.dt, ks.keys.expire, ks.keys.rv_t,
                  ks.keys.rv_node)


def _cnt_hashes(ks: KeySpace, kcrc: np.ndarray
                ) -> tuple[np.ndarray, np.ndarray]:
    """(owning kid, hash) per counter slot."""
    kid = ks.cnt.kid
    return kid, _chain(_SEED_CNT, kcrc[kid], ks.cnt.node, ks.cnt.val,
                       ks.cnt.uuid, ks.cnt.base, ks.cnt.base_t)


def _el_hashes(ks: KeySpace, kcrc: np.ndarray
               ) -> tuple[np.ndarray, np.ndarray]:
    """(owning kid, hash) per LIVE element row (GC-dead rows excluded,
    inert tombstones normalized — see the module docstring)."""
    live = np.nonzero(ks.el.kid >= 0)[0]
    if not len(live):
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=_U64)
    kid = ks.el.kid[live]
    add_t = ks.el.add_t[live]
    del_t = ks.el.del_t[live]
    del_norm = np.where(del_t > add_t, del_t, 0)
    return kid, _chain(_SEED_EL, kcrc[kid], ks.member_crcs()[live],
                       add_t, ks.el.add_node[live], del_norm)


def _tns_hashes(ks: KeySpace, kcrc: np.ndarray
                ) -> tuple[np.ndarray, np.ndarray]:
    """(owning kid, hash) per tensor contributor slot holding a real
    write.  Payload BYTES are deliberately absent, by the same argument
    as register values (module docstring): a slot is an LWW register
    whose (node, uuid) stamp identifies the winning write, and one
    write has one payload — hashing the stamp is hashing the payload,
    with zero O(payload) passes per exchange."""
    from ..crdt.semantics import NEUTRAL_T
    n = ks.tns.n
    if not n:
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=_U64)
    live = np.nonzero(ks.tns.uuid[:n] != NEUTRAL_T)[0]
    if not len(live):
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=_U64)
    kid = ks.tns.kid[live]
    return kid, _chain(_SEED_TNS, kcrc[kid], ks.tns.node[live],
                       ks.tns.uuid[live], ks.tns.cnt[live])


def _del_hashes(ks: KeySpace) -> tuple[np.ndarray, np.ndarray]:
    """(key crc, hash) per key-tombstone record, in dict order (aligned
    with `list(ks.key_deletes)`)."""
    m = len(ks.key_deletes)
    crc32 = zlib.crc32
    dcrc = np.fromiter((crc32(k) for k in ks.key_deletes), dtype=_U64,
                       count=m)
    dts = np.fromiter(ks.key_deletes.values(), dtype=np.int64, count=m)
    return dcrc, _chain(_SEED_DEL, dcrc, dts)


def state_digest_matrix(ks: KeySpace, fanout: int,
                        leaves: int) -> np.ndarray:
    """The (fanout, leaves) uint64 digest matrix of `ks`'s logical state
    (see module docstring).  Callers owning a deferring engine must
    flush it first — the fold reads host columns."""
    flat = np.zeros(fanout * leaves, dtype=_U64)
    n = ks.keys.n
    kcrc = ks.key_crcs()
    if n:
        kb = _buckets(kcrc, fanout, leaves)
        np.add.at(flat, kb, _env_hashes(ks, kcrc))
        if ks.cnt.n:
            kid, h = _cnt_hashes(ks, kcrc)
            np.add.at(flat, kb[kid], h)
        if ks.el.n:
            kid, h = _el_hashes(ks, kcrc)
            if len(kid):
                np.add.at(flat, kb[kid], h)
        if ks.tns.n:
            kid, h = _tns_hashes(ks, kcrc)
            if len(kid):
                np.add.at(flat, kb[kid], h)
    if ks.key_deletes:
        dcrc, h = _del_hashes(ks)
        np.add.at(flat, _buckets(dcrc, fanout, leaves), h)
    return flat.reshape(fanout, leaves)


def full_state_digest(ks: KeySpace, fanout: int = 0,
                      leaves: int = 1) -> int:
    """One 64-bit digest of the whole logical state: the matrix folded
    to a scalar (mod-2^64 sum, so it is geometry-independent — every
    (fanout, leaves) layout of the same state sums to the same value).
    The chaos oracle's digest-agreement law and the resync bench both
    compare replicas through this; same flush-first caveat as
    `state_digest_matrix`."""
    if fanout <= 0:
        fanout = DIGEST_FANOUT
    m = state_digest_matrix(ks, fanout, leaves)
    return int(m.sum(dtype=_U64))


def _key_accum(ks: KeySpace) -> np.ndarray:
    """Per-kid uint64 content stamp: each live key's total contribution
    to its digest bucket (envelope row + counter slots + live element
    rows; tombstone records ride separately — `_del_hashes`).  Derived
    from the SAME row hashes `state_digest_matrix` folds, so a bucket's
    digest is exactly the sum of its keys' stamps plus its tombstone
    hashes — the digest levels cannot disagree."""
    n = ks.keys.n
    acc = np.zeros(n, dtype=_U64)
    if n:
        kcrc = ks.key_crcs()
        acc += _env_hashes(ks, kcrc)
        if ks.cnt.n:
            kid, h = _cnt_hashes(ks, kcrc)
            np.add.at(acc, kid, h)
        if ks.el.n:
            kid, h = _el_hashes(ks, kcrc)
            if len(kid):
                np.add.at(acc, kid, h)
        if ks.tns.n:
            kid, h = _tns_hashes(ks, kcrc)
            if len(kid):
                np.add.at(acc, kid, h)
    return acc


def bucket_key_sel(ks: KeySpace, fanout: int, leaves: int,
                   mask_flat: np.ndarray) -> np.ndarray:
    """Row indices (kids) of the keys owned by the masked buckets."""
    n = ks.keys.n
    if not n:
        return np.zeros(0, dtype=np.int64)
    return np.nonzero(mask_flat[_buckets(ks.key_crcs(), fanout,
                                         leaves)])[0]


def masked_key_count(ks: KeySpace, fanout: int, leaves: int,
                     mask_flat: np.ndarray, key_sel=None) -> int:
    """Upper bound on the KeyStampTable entry count for the masked
    buckets (live keys + tombstone records; crc collisions merge
    entries, so the real table is never larger).  Bucket math over the
    cached crcs only — the cheap gate replica/link.py checks BEFORE
    paying the O(keyspace) `_key_accum` pass a stamp table costs.
    `key_sel`: a precomputed `bucket_key_sel` result to reuse."""
    if key_sel is None:
        key_sel = bucket_key_sel(ks, fanout, leaves, mask_flat)
    n = len(key_sel)
    if ks.key_deletes:
        crc32 = zlib.crc32
        dcrc = np.fromiter((crc32(k) for k in ks.key_deletes),
                           dtype=_U64, count=len(ks.key_deletes))
        n += int(mask_flat[_buckets(dcrc, fanout, leaves)].sum())
    return n


def export_bucket_batch(ks: KeySpace, fanout: int, leaves: int,
                        mask_flat: np.ndarray) -> ColumnarBatch:
    """One deduplicated whole-state batch of exactly the keys (and their
    counter/element rows, and the key tombstones) owned by the masked
    buckets — the range-scoped delta a pusher streams for divergent
    buckets (replica/link.py _send_delta via
    persist/snapshot.write_snapshot_file)."""
    sel = bucket_key_sel(ks, fanout, leaves, mask_flat)
    b = batch_from_keyspace(ks, include_deletes=False, key_sel=sel)
    if ks.key_deletes:
        crc32 = zlib.crc32
        m = len(ks.key_deletes)
        dcrc = np.fromiter((crc32(k) for k in ks.key_deletes), dtype=_U64,
                           count=m)
        dsel = np.nonzero(mask_flat[_buckets(dcrc, fanout, leaves)])[0]
        if len(dsel):
            keys = list(ks.key_deletes)
            b.del_keys = [keys[i] for i in dsel]
            b.del_t = np.fromiter(ks.key_deletes.values(),
                                  dtype=np.int64, count=m)[dsel]
    return b


class KeyStampTable:
    """The per-key refinement level of the digest exchange (level 2):
    one `(crc32(key), content stamp)` entry per distinct key crc in the
    masked (divergent) buckets, where the stamp is the mod-2^64 sum of
    every local contribution hashing to that crc — live rows via
    `_key_accum`, tombstone records via `_del_hashes`.  Keying entries
    by crc (not kid) makes both sides' tables comparable without
    exchanging key bytes, and makes crc32 collisions SAFE by
    construction: colliding keys share one entry on both sides, so a
    content difference in either key flags the entry and streams them
    all — collisions can only cost bytes, never convergence.

    The pusher sends `crcs`/`stamps`; the peer replies with the entry
    indices whose stamp differs from (or is absent in) its own table
    (`stamp_mismatch_indices`), and `export_batch` then ships exactly
    those entries' keys — the whole-bucket export minus the innocent
    bystanders that merely share a bucket with a divergent key."""

    def __init__(self, ks: KeySpace, fanout: int, leaves: int,
                 mask_flat: np.ndarray, key_sel=None):
        # `key_sel`: a precomputed `bucket_key_sel` result to reuse (the
        # gate in replica/link.py already paid the bucket pass)
        sel = key_sel if key_sel is not None else \
            bucket_key_sel(ks, fanout, leaves, mask_flat)
        crcs = [ks.key_crcs()[sel]] if len(sel) else []
        stamps = [_key_accum(ks)[sel]] if len(sel) else []
        self._kids = sel
        self._del_keys: list[bytes] = []
        self._del_t = np.zeros(0, dtype=np.int64)
        if ks.key_deletes:
            dcrc, dh = _del_hashes(ks)
            dsel = np.nonzero(mask_flat[_buckets(dcrc, fanout,
                                                 leaves)])[0]
            if len(dsel):
                keys = list(ks.key_deletes)
                self._del_keys = [keys[i] for i in dsel]
                self._del_t = np.fromiter(
                    (ks.key_deletes[k] for k in self._del_keys),
                    dtype=np.int64, count=len(self._del_keys))
                crcs.append(dcrc[dsel])
                stamps.append(dh[dsel])
        allcrc = np.concatenate(crcs) if crcs else np.zeros(0, _U64)
        allstamp = np.concatenate(stamps) if stamps else \
            np.zeros(0, _U64)
        self.crcs, inv = np.unique(allcrc, return_inverse=True)
        self.stamps = np.zeros(len(self.crcs), dtype=_U64)
        np.add.at(self.stamps, inv, allstamp)
        self._kid_entry = inv[:len(self._kids)]
        self._del_entry = inv[len(self._kids):]

    def export_batch(self, ks: KeySpace,
                     selected: np.ndarray) -> ColumnarBatch:
        """The delta batch for the selected entry indices: exactly those
        entries' live keys (deduplicated whole-state rows) and tombstone
        records — `export_bucket_batch` narrowed from dirty buckets to
        divergent keys."""
        pick = np.zeros(len(self.crcs), dtype=bool)
        pick[selected] = True
        b = batch_from_keyspace(ks, include_deletes=False,
                                key_sel=self._kids[pick[self._kid_entry]])
        if self._del_keys:
            dm = pick[self._del_entry]
            if dm.any():
                b.del_keys = [k for k, m in zip(self._del_keys, dm) if m]
                b.del_t = self._del_t[dm]
        return b


def stamp_mismatch_indices(ks: KeySpace, crcs: np.ndarray,
                           stamps: np.ndarray) -> np.ndarray:
    """The puller leg of the level-2 exchange: indices of the peer's
    stamp entries whose crc has a different (or no) summed stamp on this
    store — the keys the peer must stream.  Local keys the peer did not
    list are invisible here ON PURPOSE: merge never deletes, so
    puller-only state is not this exchange's problem — it flows back
    through OUR push leg toward the peer.  A crc determines its bucket,
    so local contributions are collected keyspace-wide (any local key
    sharing a listed crc shares its bucket too)."""
    parts_c, parts_s = [], []
    if ks.keys.n:
        kcrc = ks.key_crcs()
        m = np.isin(kcrc, crcs)
        if m.any():
            idx = np.nonzero(m)[0]
            parts_c.append(kcrc[idx])
            parts_s.append(_key_accum(ks)[idx])
    if ks.key_deletes:
        dcrc, dh = _del_hashes(ks)
        dm = np.isin(dcrc, crcs)
        if dm.any():
            parts_c.append(dcrc[dm])
            parts_s.append(dh[dm])
    if not parts_c:
        return np.arange(len(crcs), dtype=np.int64)  # all absent here
    oc = np.concatenate(parts_c)
    os_ = np.concatenate(parts_s)
    uniq, inv = np.unique(oc, return_inverse=True)
    mine = np.zeros(len(uniq), dtype=_U64)
    np.add.at(mine, inv, os_)
    pos = np.searchsorted(uniq, crcs)
    posc = np.clip(pos, 0, len(uniq) - 1)
    have = uniq[posc] == crcs
    differ = ~have | (mine[posc] != stamps)
    return np.nonzero(differ)[0]


def sum_matrices(mats, fanout: int, leaves: int) -> np.ndarray:
    """Aggregate per-shard matrices (raw uint64 LE buffers or arrays)
    into one (fanout, leaves) matrix — shards partition the keys, and
    the fold is an unordered sum, so plane-wide = Σ per-worker."""
    out = np.zeros(fanout * leaves, dtype=_U64)
    for m in mats:
        arr = m if isinstance(m, np.ndarray) else np.frombuffer(m, _U64)
        if arr.size != out.size:
            raise ValueError(
                f"digest matrix size mismatch: {arr.size} != {out.size}")
        out = out + arr.reshape(-1)
    return out.reshape(fanout, leaves)
