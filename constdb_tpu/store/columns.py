"""Growable struct-of-arrays column group.

The keyspace's numeric plane lives in these instead of per-key heap objects:
columns are contiguous numpy arrays, so bulk merge stages to the device with
zero per-row Python work and merged columns write back with fancy indexing.
"""

from __future__ import annotations

import numpy as np


class Columns:
    """A set of equally-sized growable numpy columns (amortized doubling)."""

    def __init__(self, spec: dict[str, np.dtype], cap: int = 1024):
        self._spec = {k: np.dtype(v) for k, v in spec.items()}
        self._cap = max(cap, 16)
        self.n = 0
        for name, dt in self._spec.items():
            setattr(self, "_" + name, np.zeros(self._cap, dtype=dt))

    def _grow(self, need: int) -> None:
        cap = self._cap
        while cap < need:
            cap *= 2
        for name in self._spec:
            old = getattr(self, "_" + name)
            new = np.zeros(cap, dtype=old.dtype)
            new[: self.n] = old[: self.n]
            setattr(self, "_" + name, new)
        self._cap = cap
        self._drop_views()

    def _drop_views(self) -> None:
        for name in self._spec:
            self.__dict__.pop(name, None)

    def append(self, **vals) -> int:
        row = self.n
        if row >= self._cap:
            self._grow(row + 1)
        self.n = row + 1
        self._drop_views()  # length-n views are stale
        for name, v in vals.items():
            getattr(self, "_" + name)[row] = v
        return row

    def append_block(self, n: int, **arrays) -> np.ndarray:
        """Append n rows from aligned arrays; returns the new row indices."""
        start = self.n
        if start + n > self._cap:
            self._grow(start + n)
        self.n = start + n
        self._drop_views()
        for name, arr in arrays.items():
            getattr(self, "_" + name)[start:start + n] = arr
        return np.arange(start, start + n, dtype=np.int64)

    def col(self, name: str) -> np.ndarray:
        """Live view of a column (length n)."""
        return getattr(self, name)

    def __getattr__(self, name: str):
        # cols.ct -> live [0, n) view, CACHED as a real instance attribute so
        # repeat access costs a dict hit, not a slice build (the op path
        # touches columns ~10x per command).  append/_grow drop the caches.
        spec = object.__getattribute__(self, "_spec")
        if name in spec:
            view = object.__getattribute__(self, "_" + name)[
                : object.__getattribute__(self, "n")]
            object.__setattr__(self, name, view)
            return view
        raise AttributeError(name)

    def __len__(self) -> int:
        return self.n

    def nbytes(self) -> int:
        """Allocated bytes of every column (capacity, not just rows) —
        allocator-true accounting for INFO (reference src/lib.rs:63-78
        exposes jemalloc's allocated gauge; this is the store-exact part)."""
        return sum(getattr(self, "_" + name).nbytes for name in self._spec)

    def live_bytes(self) -> int:
        """LIVE row bytes (n rows x per-row width), independent of the
        pow2 capacity — the overload governor's accounting unit
        (server/overload.py): a hash-partitioned store's shards sum to
        exactly the single-store figure, which capacity-based accounting
        cannot (each shard rounds its capacity up separately)."""
        return self.n * sum(dt.itemsize for dt in self._spec.values())


class TensorCols(Columns):
    """Tensor contributor slots — the envelope half of the tensor plane
    (crdt/tensor.py): one row per (key, writer node), holding the LWW
    stamp (`uuid`), the avg-strategy contribution count (`cnt`), and the
    writer node id.  Payload arrays live in the keyspace's row-aligned
    `tns_payload` side list (and, under a resident engine, in the device
    payload pools of engine/tpu.py)."""

    def __init__(self) -> None:
        super().__init__({"kid": np.int64, "node": np.int64,
                          "uuid": np.int64, "cnt": np.int64}, cap=256)
