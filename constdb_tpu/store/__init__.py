from .columns import Columns
from .keyspace import KeySpace

__all__ = ["Columns", "KeySpace"]
