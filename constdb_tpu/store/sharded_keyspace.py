"""Hash-sharded keyspace: N independent KeySpace + MergeEngine pairs.

Per-key CRDT merges commute and never read another key's state (SURVEY
§2.7 "kv" axis), so the host side of a snapshot merge — staging,
native-table assigns, flush apply, the ~54s single-threaded remainder in
BENCH_r06 — shards embarrassingly by key hash, exactly as
parallel/sharded.py already shards the slot axis on-device.

Layout:
  * `shard_of` / `shard_ids` — the ONE hash (crc32, process-independent —
    Python's builtin `hash` is salted per process and workers live in
    separate processes) every router uses: batch splitting, key-routed
    canonical reads, del-tombstone fan-out.
  * `extract_shard` — one shard's sub-batch of a ColumnarBatch, with
    counter/element rows re-pointed at shard-local key positions.  Chunks
    with equal identity tokens produce equal sub-batches, so the engine's
    per-shape memoization and aligned-fold clustering keep working INSIDE
    each shard.
  * `ShardedKeySpace` — the facade bench / snapshot ingest / replica
    catch-up drive.  Three modes:
      - n_shards == 1: degenerate — one KeySpace + one engine, batches
        pass through untouched (no hashing, no splitting).  This is
        byte-identical to today's single-keyspace path BY CONSTRUCTION
        and pinned by tests/test_sharded_keyspace.py.
      - "local": N stores + engines in this process, dispatched through
        engine/tpu.py's ShardDispatcher (one device queue, interleaved).
      - "process": N worker processes (parallel/host_pool.py) — the whole
        host critical path scales with cores instead of fighting the GIL.

Ingest cadence: `submit(batch)` buffers `group` chunks, then ships the
group — process mode broadcasts ONE shared-memory segment to every worker
and consumes per-shard completions as they land (bounded in-flight window,
the process-level analogue of PR 1's double buffering).  `flush()` drains
everything and applies engine flushes, after which reads are exact.
"""

from __future__ import annotations

import os
import zlib
from typing import Optional

import numpy as np

from ..engine.base import ColumnarBatch
from .keyspace import KeySpace

_I64 = np.int64
_U8 = np.uint8

MAX_SHARDS = 64  # shard ids travel as uint8 columns; 64 cores is plenty


def default_shards() -> int:
    """CONSTDB_SHARDS, defaulting to 1 (today's exact single-keyspace
    path) on <= 2 cores — process-parallel merge needs spare cores to
    help — and to the core count (capped) above that."""
    from ..conf import env_str
    env = env_str("CONSTDB_SHARDS")
    if env:
        return max(1, min(int(env), MAX_SHARDS))
    ncpu = os.cpu_count() or 1
    if ncpu <= 2:
        return 1
    return min(ncpu, MAX_SHARDS)


def shard_of(key: bytes, n_shards: int) -> int:
    """Deterministic, process-independent key -> shard."""
    return zlib.crc32(key) % n_shards


def shard_ids(keys: list, n_shards: int) -> np.ndarray:
    """Vectorized shard column (uint8) for a key list."""
    crc = zlib.crc32
    n = len(keys)
    out = np.fromiter((crc(k) for k in keys), dtype=np.uint32, count=n)
    return (out % n_shards).astype(_U8)


def extract_shard(batch: ColumnarBatch, sids: np.ndarray,
                  del_sids: Optional[np.ndarray],
                  shard: int, memo: Optional[dict] = None) -> ColumnarBatch:
    """The sub-batch of `batch` owned by `shard`, per the `sids` shard
    column (one uint8 per batch key position; `del_sids` covers
    del_keys).  Counter/element rows re-point at shard-local key
    positions.  Identity tokens survive (suffixed with the shard), so
    replica chunks sharing a token still resolve once per shard.

    `memo`: a caller-scoped dict amortizing the REPLICA-INVARIANT parts
    of extraction — the key selection + posmap + extracted key list (per
    key token) and the element-row mask + extracted member list (per
    element token).  Replica snapshots of one keyspace share those
    planes, so with R replicas the per-item Python work runs once, not R
    times.  Equal tokens MUST imply equal plane content (the engine's
    contract); callers own the memo's lifetime."""
    nk = batch.n_keys
    sub = ColumnarBatch()
    sub.rows_unique_per_slot = batch.rows_unique_per_slot
    if batch.key_shape is not None:
        sub.key_shape = ("shard", shard, batch.key_shape)
    if batch.el_shape is not None:
        sub.el_shape = ("shard", shard, batch.el_shape)
    sub.shape_refs = batch.shape_refs
    # False is exact for any subset of an all-None list; anything else
    # re-scans (a lone dict value elsewhere must not taint this shard)
    sub.el_has_vals = False if batch.el_has_vals is False else None

    kkey = ("k", batch.key_shape, shard) \
        if memo is not None and batch.key_shape is not None else None
    cached = memo.get(kkey) if kkey is not None else None
    if cached is None:
        sel = np.nonzero(sids == shard)[0]
        keys = list(map(batch.keys.__getitem__, sel.tolist()))
        posmap = np.full(nk, -1, dtype=_I64)
        posmap[sel] = np.arange(len(sel), dtype=_I64)
        cached = (sel, keys, posmap)
        if kkey is not None:
            memo[kkey] = cached
    sel, keys, posmap = cached
    sub.keys = keys  # shared across sub-batches: engine reads only
    sub.key_enc = np.ascontiguousarray(batch.key_enc[sel])
    sub.key_ct = np.ascontiguousarray(batch.key_ct[sel])
    sub.key_mt = np.ascontiguousarray(batch.key_mt[sel])
    sub.key_dt = np.ascontiguousarray(batch.key_dt[sel])
    sub.key_expire = np.ascontiguousarray(batch.key_expire[sel])
    sub.reg_val = list(map(batch.reg_val.__getitem__, sel.tolist()))
    sub.reg_t = np.ascontiguousarray(batch.reg_t[sel])
    sub.reg_node = np.ascontiguousarray(batch.reg_node[sel])

    if len(batch.cnt_ki):
        cki = np.asarray(batch.cnt_ki)
        cm = np.nonzero(sids[cki] == shard)[0]
        sub.cnt_ki = posmap[cki[cm]]
        for col in ("cnt_node", "cnt_val", "cnt_uuid", "cnt_base",
                    "cnt_base_t"):
            setattr(sub, col,
                    np.ascontiguousarray(np.asarray(getattr(batch, col))[cm]))

    if len(batch.el_ki):
        eki = np.asarray(batch.el_ki)
        ekey = ("e", batch.el_shape, batch.key_shape, shard) \
            if memo is not None and batch.el_shape is not None else None
        ecached = memo.get(ekey) if ekey is not None else None
        if ecached is None:
            em = np.nonzero(sids[eki] == shard)[0]
            members = list(map(batch.el_member.__getitem__, em.tolist()))
            ecached = (em, members, posmap[eki[em]])
            if ekey is not None:
                memo[ekey] = ecached
        em, members, sub.el_ki = ecached
        sub.el_member = members  # shared: engine reads only
        if batch.el_has_vals is False:
            # exact: any subset of an all-None column is all None — skip
            # the per-item extraction entirely
            sub.el_val = [None] * len(em)
        else:
            sub.el_val = list(map(batch.el_val.__getitem__, em.tolist()))
        for col in ("el_add_t", "el_add_node", "el_del_t"):
            setattr(sub, col,
                    np.ascontiguousarray(np.asarray(getattr(batch, col))[em]))

    if len(batch.tns_ki):
        tki = np.asarray(batch.tns_ki)
        tm = np.nonzero(sids[tki] == shard)[0]
        if len(tm):
            sub.tns_ki = posmap[tki[tm]]
            for col in ("tns_node", "tns_uuid", "tns_cnt"):
                setattr(sub, col, np.ascontiguousarray(
                    np.asarray(getattr(batch, col))[tm]))
            idx = tm.tolist()
            sub.tns_cfg = [batch.tns_cfg[i] for i in idx]
            sub.tns_payload = [batch.tns_payload[i] for i in idx]

    if batch.del_keys:
        if del_sids is None:
            raise ValueError(
                "batch carries del_keys: the caller must supply their "
                "shard column (shard_ids(batch.del_keys, n_shards))")
        dsel = np.nonzero(del_sids == shard)[0]
        if len(dsel):
            sub.del_keys = list(map(batch.del_keys.__getitem__,
                                    dsel.tolist()))
            sub.del_t = np.ascontiguousarray(
                np.asarray(batch.del_t)[dsel])
    return sub


def keyspace_state_bytes(ks: KeySpace):
    """Exact store state — every numeric column byte plus the object
    planes.  Stricter than canonical(): the differential tests pin the
    sharded paths BYTE-identical to the single-keyspace path, not merely
    semantically equal."""
    n, c, e, t = ks.keys.n, ks.cnt.n, ks.el.n, ks.tns.n
    return (
        {name: ks.keys.col(name)[:n].tobytes()
         for name in ("enc", "ct", "mt", "dt", "expire", "rv_t", "rv_node",
                      "cnt_sum")},
        {name: ks.cnt.col(name)[:c].tobytes()
         for name in ("kid", "node", "val", "uuid", "base", "base_t")},
        {name: ks.el.col(name)[:e].tobytes()
         for name in ("kid", "add_t", "add_node", "del_t")},
        {name: ks.tns.col(name)[:t].tobytes()
         for name in ("kid", "node", "uuid", "cnt")},
        list(ks.key_bytes), list(ks.reg_val), list(ks.el_member),
        list(ks.el_val),
        [None if p is None else p.tobytes() for p in ks.tns_payload],
        dict(ks.key_deletes), sorted(ks.garbage),
    )


class ShardedKeySpace:
    """N hash-partitioned KeySpace + MergeEngine pairs behind one ingest
    facade (see module docstring for modes and cadence)."""

    def __init__(self, n_shards: Optional[int] = None, mode: str = "auto",
                 engine_spec: str = "tpu", engine_factory=None,
                 group: int = 8, max_inflight: int = 2,
                 env: Optional[dict] = None):
        self.n_shards = default_shards() if n_shards is None \
            else max(1, min(int(n_shards), MAX_SHARDS))
        if mode == "auto":
            mode = "process" if self.n_shards > 1 else "local"
        self.mode = mode if self.n_shards > 1 else "local"
        self.engine_spec = engine_spec
        self._engine_factory = engine_factory
        self.group = max(1, group)
        self._buf: list[ColumnarBatch] = []
        self._sid_memo: dict = {}   # key_shape -> (sids, pin)
        self._tok_serial = 0
        self.pool = None
        self.stores: list[KeySpace] = []
        self.dispatcher = None
        self._engine = None  # degenerate single-shard engine
        if self.n_shards == 1:
            self.stores = [KeySpace()]
            self._engine = engine_factory() if engine_factory is not None \
                else self._default_engine()
        elif self.mode == "process":
            from ..parallel.host_pool import HostShardPool
            self.pool = HostShardPool(self.n_shards,
                                      engine_spec=engine_spec,
                                      max_inflight=max_inflight, env=env)
        elif self.mode == "local":
            from ..engine.tpu import ShardDispatcher
            self.stores = [KeySpace() for _ in range(self.n_shards)]
            self.dispatcher = ShardDispatcher(self.n_shards,
                                              engine_factory=engine_factory)
        else:
            raise ValueError(f"unknown shard mode {mode!r}")

    def _default_engine(self):
        if self.engine_spec == "cpu":
            from ..engine.cpu import CpuMergeEngine
            return CpuMergeEngine()
        from ..engine.tpu import TpuMergeEngine
        return TpuMergeEngine(resident=True)

    # -------------------------------------------------------------- ingest

    def submit(self, batch: ColumnarBatch) -> None:
        """Queue one columnar batch; ships when `group` are buffered."""
        self._buf.append(batch)
        if len(self._buf) >= self.group:
            self._ship()

    def submit_raw(self, payload: bytes) -> None:
        """Queue one ENCODED batch section (snapshot codec bytes).  In
        process mode the payload ships to the workers as-is — they decode
        AND hash the keys in parallel, so the parent pays only the
        buffer copy; other modes decode here."""
        if self.pool is None:
            from ..persist.snapshot import _decode_batch
            self.submit(_decode_batch(payload))
            return
        self._buf.append(bytes(payload))
        if len(self._buf) >= self.group:
            self._ship()

    def submit_batches(self, batches: list) -> None:
        for b in batches:
            self.submit(b)

    def _sids_for(self, batch: ColumnarBatch) -> np.ndarray:
        """Shard column for a batch's keys, memoized by identity token
        (replica chunks of one keyspace share tokens — hash once, not
        once per replica).  Memo entries pin the parent planes via
        shape_refs so a recycled id can never alias; the memo clears at
        every group boundary, which bounds what it pins to one group."""
        tok = batch.key_shape
        if tok is None:
            return shard_ids(batch.keys, self.n_shards)
        hit = self._sid_memo.get(tok)
        if hit is not None:
            return hit[0]
        sids = shard_ids(batch.keys, self.n_shards)
        self._sid_memo[tok] = (sids, batch.shape_refs)
        return sids

    def _ship(self) -> None:
        batches, self._buf = self._buf, []
        if not batches:
            return
        if self.n_shards == 1:
            self._engine.merge_many(self.stores[0], batches)
            return
        if self.mode == "local":
            sid_cols = [self._sids_for(b) for b in batches]
            dsid_cols = [shard_ids(b.del_keys, self.n_shards)
                         if b.del_keys else None for b in batches]
            for s in range(self.n_shards):
                subs = [sub for b, sids, dsids in
                        zip(batches, sid_cols, dsid_cols)
                        if (sub := extract_shard(b, sids, dsids, s)).n_rows
                        or sub.del_keys]
                if subs:
                    self.dispatcher.merge_shard(s, self.stores[s], subs)
            self._sid_memo.clear()
            return
        # process mode: encode once, broadcast the segment to every worker.
        # Bytes planes shared by replica chunks (same identity token —
        # the keys of a range, its member list) are encoded ONCE per job
        # and referenced by plane id: with R replicas both the parent's
        # encode and every worker's decode do 1/R of the per-item work.
        from ..persist.snapshot import _encode_batch, _write_bytes_list
        from ..utils.varint import write_uvarint
        planes: list = []
        plane_of: dict = {}
        entries = []
        pins = []

        def plane_id(kind, tok, items) -> int:
            pid = plane_of.get((kind, tok))
            if pid is None:
                buf = bytearray()
                write_uvarint(buf, len(items))
                _write_bytes_list(buf, items)
                pid = len(planes)
                planes.append(bytes(buf))
                plane_of[(kind, tok)] = pid
            return pid

        for b in batches:
            if isinstance(b, bytes):  # raw section payload: workers
                entries.append((b, None, None, None, -1, -1))
                continue  # decode + hash it themselves, in parallel
            # identity tokens are rewritten to run-unique serials: the
            # parent's id()-based tuples are only unique while the parent
            # objects live, but a serial handed to a worker stays valid
            # forever (equal serial <=> equal parent token within this
            # group, guaranteed by the pins below)
            tok_k = self._remap_token(b.key_shape)
            tok_e = self._remap_token(b.el_shape)
            kpid = plane_id("k", tok_k, b.keys) if tok_k is not None else -1
            epid = plane_id("e", tok_e, b.el_member) \
                if tok_e is not None and len(b.el_ki) else -1
            payload = bytes(_encode_batch(b, skip_keys=kpid >= 0,
                                          skip_members=epid >= 0))
            entries.append((payload, tok_k, tok_e, b.el_has_vals,
                            kpid, epid))
            pins.append(b.shape_refs)
        self.pool.submit_group(planes, entries, pins)
        self._sid_memo.clear()
        self._tok_map = {}

    def _remap_token(self, tok):
        if tok is None:
            return None
        m = getattr(self, "_tok_map", None)
        if m is None:
            m = self._tok_map = {}
        got = m.get(tok)
        if got is None:
            self._tok_serial += 1
            got = m[tok] = ("tok", self._tok_serial)
        return got

    def barrier(self) -> None:
        """Ship any partial group and drain in-flight merges."""
        self._ship()
        if self.pool is not None:
            self.pool.barrier()

    def flush(self) -> None:
        """Barrier + engine flush on every shard: reads are exact after
        this returns."""
        self.barrier()
        if self.n_shards == 1:
            if getattr(self._engine, "needs_flush", False):
                self._engine.flush(self.stores[0])
        elif self.mode == "local":
            self.dispatcher.flush_all(self.stores)
        else:
            self.pool.call_all("flush")

    # --------------------------------------------------------------- reads

    def canonical(self, keys=None) -> dict:
        """Union of per-shard canonical states (shards hold disjoint
        keys).  `keys` routes each key to its owning shard.  Implicitly
        flushes: reads are exact, whichever mode is active."""
        if self.pool is not None:
            self.flush()  # ship the partial buffer + worker engine flush
            if keys is None:
                parts = self.pool.call_all("canonical", None)
            else:
                per = self._route_keys(keys)
                parts = [self.pool.call_one(s, "canonical", per[s])
                         for s in range(self.n_shards) if per[s]]
            out: dict = {}
            for p in parts:
                out.update(p)
            return out
        self.flush()
        out = {}
        if keys is None:
            for ks in self.stores:
                out.update(ks.canonical())
            return out
        per = self._route_keys(keys)
        for s, ks in enumerate(self.stores):
            if per[s]:
                out.update(ks.canonical(keys=per[s]))
        return out

    def _route_keys(self, keys) -> list[list]:
        per: list[list] = [[] for _ in range(self.n_shards)]
        if self.n_shards == 1:
            per[0] = list(keys)
            return per
        for k in keys:
            per[shard_of(k, self.n_shards)].append(k)
        return per

    def n_keys(self) -> int:
        return sum(m["keys"] for m in self.memory_report_per_shard())

    def memory_report_per_shard(self) -> list[dict]:
        self.flush()
        if self.pool is not None:
            return self.pool.call_all("memory")
        return [ks.memory_report() for ks in self.stores]

    def state_bytes_per_shard(self) -> list:
        """Per-shard exact state (differential tests)."""
        self.flush()
        if self.pool is not None:
            return self.pool.call_all("state_bytes")
        return [keyspace_state_bytes(ks) for ks in self.stores]

    def host_secs_per_shard(self) -> list[dict]:
        """Per-shard engine timers ({family_secs, stage_secs}) — bench
        emits these so the next round can see whether cnt/el/flush
        actually split across cores."""
        if self.pool is not None:
            return self.pool.call_all("secs")
        engines = [self._engine] if self.n_shards == 1 \
            else self.dispatcher.engines
        return [{"family_secs": dict(getattr(e, "family_secs", {}) or {}),
                 "stage_secs": dict(getattr(e, "stage_secs", {}) or {}),
                 "bytes_h2d": getattr(e, "bytes_h2d", 0),
                 "bytes_d2h": getattr(e, "bytes_d2h", 0),
                 "folds": getattr(e, "folds", 0),
                 "dev_rounds_resident": getattr(e, "dev_rounds_resident", 0),
                 "host_micro_rounds": getattr(e, "host_micro_rounds", 0),
                 "flush_rows_downloaded":
                     getattr(e, "flush_rows_downloaded", 0),
                 "flush_rows_full_equiv":
                     getattr(e, "flush_rows_full_equiv", 0)}
                for e in engines]

    # ------------------------------------------------------- consolidation

    def export_batches(self):
        """Whole-state columnar export of every shard (one batch per
        shard, disjoint keys) — the consolidation feed: a node that
        sharded a catch-up merges these N deduplicated batches into its
        serving keyspace in one engine pass.  Materializes ALL shards at
        once; large-state consolidation should stream
        `export_shard_batch(s, free=True)` shard by shard instead."""
        self.flush()
        if self.pool is not None:
            from ..persist.snapshot import _decode_batch
            return [_decode_batch(p) for p in self.pool.export_all()]
        from ..engine.base import batch_from_keyspace
        return [batch_from_keyspace(ks) for ks in self.stores]

    def export_shard_batch(self, shard: int, free: bool = False):
        """ONE shard's whole-state export.  `free=True` drops that
        shard's store (and engine state) right after the export, so a
        streaming consolidation holds at most one shard's state twice —
        the N-shard snapshot of `export_batches` would double the whole
        keyspace's footprint at exactly the multi-GB scale the sharded
        ingest targets."""
        self.flush()
        if self.pool is not None:
            from ..persist.snapshot import _decode_batch
            payload = self.pool.export_shard(shard)
            if free:
                self.pool.call_one(shard, "reset")
            return _decode_batch(payload)
        from ..engine.base import batch_from_keyspace
        b = batch_from_keyspace(self.stores[shard])
        if free:
            eng = self._engine if self.n_shards == 1 \
                else self.dispatcher.engines[shard]
            if hasattr(eng, "discard_resident"):
                eng.discard_resident()  # flushed above: nothing unsynced
            self.stores[shard] = KeySpace()
        return b

    def consolidate_into(self, ks: KeySpace, engine) -> None:
        """Merge every shard's merged state into `ks` through `engine`.
        Shard exports are deduplicated (one row per slot) and disjoint,
        so this is a single cheap pass regardless of how many replica
        snapshots fed the shards."""
        batches = [b for b in self.export_batches()
                   if b.n_rows or b.del_keys]
        if not batches:
            return
        if hasattr(engine, "merge_many"):
            engine.merge_many(ks, batches)
        else:  # pragma: no cover - minimal engines
            for b in batches:
                engine.merge(ks, b)

    # ----------------------------------------------------------- lifecycle

    def reset(self) -> None:
        """Fresh stores AND engines on every shard (bench repeats:
        engine timers/counters restart, resident state drops)."""
        self._buf.clear()
        self._sid_memo.clear()
        if self.pool is not None:
            self.pool.call_all("reset")
            self.pool.rows_merged = [0] * self.n_shards
            return
        if self.n_shards == 1:
            if hasattr(self._engine, "close"):
                self._engine.close()
            self._engine = self._engine_factory() \
                if self._engine_factory is not None \
                else self._default_engine()
            self.stores = [KeySpace()]
            return
        from ..engine.tpu import ShardDispatcher
        self.dispatcher.close()
        self.dispatcher = ShardDispatcher(self.n_shards,
                                          engine_factory=self._engine_factory)
        self.stores = [KeySpace() for _ in range(self.n_shards)]

    def close(self) -> None:
        if self.pool is not None:
            self.pool.close()
        if self.dispatcher is not None:
            self.dispatcher.close()
        if self._engine is not None and hasattr(self._engine, "close"):
            self._engine.close()
