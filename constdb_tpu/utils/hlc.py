"""Hybrid-logical-clock uuid generation.

Behavior parity with reference src/server.rs:156-177 (`next_uuid`): a uuid is
`(unix_ms << 22) | seq` — 41 bits of wall-clock milliseconds and a 22-bit
per-millisecond sequence.  It doubles as the HLC timestamp that totally orders
writes across the cluster (ties across nodes are resolved by CRDT tie-break
rules, see crdt/semantics.py).

Deliberate fixes over the reference:
  * monotonic under wall-clock regression (the reference emits a smaller uuid
    if the OS clock steps back);
  * sequence overflow rolls into the millisecond field instead of wrapping.
"""

from __future__ import annotations

import time

SEQ_BITS = 22
SEQ_MASK = (1 << SEQ_BITS) - 1
UUID_MAX = (1 << 63) - 1


def now_ms() -> int:
    return time.time_ns() // 1_000_000


def now_secs() -> int:
    return int(time.time())


def uuid_ms(uuid: int) -> int:
    return uuid >> SEQ_BITS


def uuid_seq(uuid: int) -> int:
    return uuid & SEQ_MASK


class HLC:
    """Monotonic uuid/timestamp source for one node.

    `tick(is_write)` parities reference `Server::next_uuid`: a write always
    receives a strictly greater uuid than any previously issued one; reads
    re-observe the clock without consuming sequence numbers.
    """

    __slots__ = ("_uuid", "_clock")

    def __init__(self, clock=now_ms):
        self._uuid = 1
        self._clock = clock

    @property
    def current(self) -> int:
        return self._uuid

    def observe(self, remote_uuid: int) -> None:
        """Advance past a remote uuid (keeps local write uuids fresh even when
        a peer's clock is ahead)."""
        if remote_uuid > self._uuid:
            self._uuid = remote_uuid

    def tick(self, is_write: bool) -> int:
        prev_ms, seq = self._uuid >> SEQ_BITS, self._uuid & SEQ_MASK
        now = self._clock()
        if now > prev_ms:
            ms, seq = now, 0
        else:
            # clock stalled or stepped back: stay on prev_ms, bump seq on write
            ms = prev_ms
            if is_write:
                seq += 1
                if seq > SEQ_MASK:
                    ms, seq = ms + 1, 0
        if not is_write and ms == prev_ms:
            # a read never needs a fresh sequence number
            return self._uuid
        nxt = (ms << SEQ_BITS) | seq
        if is_write and nxt <= self._uuid:
            nxt = self._uuid + 1
        self._uuid = nxt
        return self._uuid
