"""Variable-width integer encoding for the snapshot format.

Same capability as reference src/snapshot.rs:25-37/244-264 (1/2/4/9-byte
envelope selected by magnitude, tag in the top 2 bits), redesigned to be
well-defined for the full signed 64-bit range:

  tag 0 (1 byte):  value in [0, 2^6)       0b00vvvvvv
  tag 1 (2 bytes): value in [0, 2^14)      0b01vvvvvv vvvvvvvv   (big-endian)
  tag 2 (4 bytes): value in [0, 2^30)      0b10vvvvvv ...        (big-endian)
  tag 3 (9 bytes): any u64                 0b11000000 + 8 BE bytes

Signed values use zigzag mapping (the reference's encoder silently corrupts
negatives — SURVEY.md §2.6).
"""

from __future__ import annotations

_TAG3 = 0b11000000


def write_uvarint(out: bytearray, v: int) -> None:
    if v < 0:
        raise ValueError("uvarint must be non-negative")
    if v < 1 << 6:
        out.append(v)
    elif v < 1 << 14:
        out += (v | (0b01 << 14)).to_bytes(2, "big")
    elif v < 1 << 30:
        out += (v | (0b10 << 30)).to_bytes(4, "big")
    elif v < 1 << 64:
        out.append(_TAG3)
        out += v.to_bytes(8, "big")
    else:
        raise ValueError("uvarint out of range")


def zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63) if v < 0 else v << 1


def unzigzag(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


def write_varint(out: bytearray, v: int) -> None:
    if not (-(1 << 63) <= v < (1 << 63)):
        raise ValueError("varint out of i64 range")
    write_uvarint(out, zigzag(v))


def read_uvarint(buf, pos: int) -> tuple[int, int]:
    """-> (value, next_pos). Raises IndexError on truncated input."""
    flag = buf[pos]
    tag = flag >> 6
    if tag == 0:
        return flag, pos + 1
    if tag == 1:
        end = pos + 2
        if end > len(buf):
            raise IndexError("truncated varint")
        v = int.from_bytes(buf[pos:end], "big") & ((1 << 14) - 1)
        if v < 1 << 6:
            raise ValueError("non-canonical varint (overlong 2-byte form)")
        return v, end
    if tag == 2:
        end = pos + 4
        if end > len(buf):
            raise IndexError("truncated varint")
        v = int.from_bytes(buf[pos:end], "big") & ((1 << 30) - 1)
        if v < 1 << 14:
            raise ValueError("non-canonical varint (overlong 4-byte form)")
        return v, end
    if flag != _TAG3:
        raise ValueError("non-canonical varint (tag-3 flag low bits set)")
    end = pos + 9
    if end > len(buf):
        raise IndexError("truncated varint")
    v = int.from_bytes(buf[pos + 1:end], "big")
    if v < 1 << 30:
        raise ValueError("non-canonical varint (overlong 9-byte form)")
    return v, end


def read_varint(buf, pos: int) -> tuple[int, int]:
    u, nxt = read_uvarint(buf, pos)
    return unzigzag(u), nxt


class VarintReader:
    """Cursor-style reader over a bytes-like object."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def uvarint(self) -> int:
        v, self.pos = read_uvarint(self.buf, self.pos)
        return v

    def varint(self) -> int:
        v, self.pos = read_varint(self.buf, self.pos)
        return v

    def take(self, n: int) -> bytes:
        end = self.pos + n
        if end > len(self.buf):
            raise IndexError("truncated bytes")
        b = bytes(self.buf[self.pos:end])
        self.pos = end
        return b

    def byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    @property
    def remaining(self) -> int:
        return len(self.buf) - self.pos
