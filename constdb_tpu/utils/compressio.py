"""Chunked compression framing shared by the broadcast replication plane.

One wire/disk container format serves three transports (the ISSUE-13
"compressed bulk sync" surface):

  * REPLBATCH payloads above the CONSTDB_WIRE_COMPRESS_MIN floor
    (replica/link.py push side, replica/coalesce.py receive side);
  * whole FULLSYNC / DELTASYNC raw windows — the compressed snapshot
    container IS the streamed file, so the pusher compresses once per
    dump, not once per peer (persist/share.py);
  * on-disk snapshot dumps (persist/snapshot.py: cron, shutdown, boot
    restore), magic-tagged so pre-PR plain files stay loadable.

Layout (all integers little-endian):

    magic   b"CSTPUZ1\\n" (8 bytes)
    alg     1 byte — 1 = zlib (streams), 2 = lzma (bulk containers);
            a decoder seeing an unknown alg raises, never guesses
    chunk*:
        comp_len  u32 (0 terminates the stream)
        filt      u8 — pre-compression filter: 0 = none, 1 = stride-8
                  byte transposition (below)
        raw_len   u32
        crc       u32 — crc32 of the RAW chunk bytes (post-unfilter, so
                  the check covers the whole decode pipeline)
        payload   comp_len bytes
    end     u32 0

The transposition filter is the classic columnar shuffle: a chunk of a
snapshot stream is dominated by little-endian i64 planes (HLC uuid
columns), whose high bytes are near-constant and whose low bytes drift
slowly when the dump iterates keys in creation order.  Regrouping every
8th byte turns those planes into long near-constant lanes that deflate
crushes — measured 3-4x smaller containers on uuid-ordered keyspace
dumps, while pure-text chunks keep filter 0 (the writer picks per chunk
by trial when asked to).

Integrity is STRUCTURAL and per-chunk: every decoder validates magic,
alg, chunk geometry (bounded lengths, so a crafted header cannot force
an unbounded allocation before validation catches up), the filter tag,
the declared raw length, and the raw crc.  Any defect — truncation, bit
flip, trailing garbage — raises `CompressFormatError`; a consumer never
acts on bytes it could not fully validate.  The replication link treats
that error as a LOUD per-peer demotion (repl_wire_demotions discipline,
watermark untouched); the snapshot loader surfaces it as
InvalidSnapshot through its normal corruption path.
"""

from __future__ import annotations

import zlib
from typing import IO, Optional

import numpy as np

from ..errors import CstError

try:
    import lzma
except ImportError:  # pragma: no cover - stripped-down stdlib
    lzma = None

MAGIC = b"CSTPUZ1\n"
ALG_ZLIB = 1
ALG_LZMA = 2      # the bulk-container alg: ~20% smaller than zlib on
#                   transposed columnar streams at ~30MB/s (preset 1);
#                   decoders accept both, writers fall back to zlib on
#                   a stripped stdlib without the lzma module

FILT_NONE = 0
FILT_TRANSPOSE8 = 1

# hard ceilings: chunk geometry a decoder accepts before allocating.
# Writers never exceed _CHUNK_RAW; anything larger is corruption.
_CHUNK_RAW = 1 << 22
_HEAD = len(MAGIC) + 1
_DEFAULT_CHUNK = 1 << 18


class CompressFormatError(CstError):
    """Malformed/corrupt compressed container (any transport)."""


def _check_alg(alg: int) -> None:
    if alg == ALG_LZMA and lzma is None:  # pragma: no cover
        raise CompressFormatError("lzma container on an lzma-less build")
    if alg not in (ALG_ZLIB, ALG_LZMA):
        raise CompressFormatError(f"unknown compression alg {alg}")


def _alg_tag(alg: str) -> int:
    if alg == "lzma" and lzma is not None:
        return ALG_LZMA
    return ALG_ZLIB


def _deflate(raw: bytes, level: int, alg: int) -> bytes:
    if alg == ALG_LZMA:
        # preset 1: the speed/ratio knee for one-pass bulk streams
        # (higher presets pay seconds per 100MB for a few percent)
        return lzma.compress(raw, preset=min(max(level // 4, 1), 6))
    return zlib.compress(raw, level)


def _transpose8(raw: bytes) -> bytes:
    """Stride-8 byte transposition (self-inverse up to reshape order):
    byte i of little-endian word j moves to lane i — i64 planes become
    8 contiguous lanes of their per-byte streams."""
    a = np.frombuffer(raw, dtype=np.uint8)
    n8 = len(a) - (len(a) % 8)
    return a[:n8].reshape(-1, 8).T.tobytes() + raw[n8:]


def _untranspose8(data: bytes) -> bytes:
    a = np.frombuffer(data, dtype=np.uint8)
    n8 = len(a) - (len(a) % 8)
    return a[:n8].reshape(8, -1).T.tobytes() + data[n8:]


def _filter_chunk(raw: bytes, level: int, filt: str, alg: int):
    """-> (filt_tag, compressed) for one raw chunk.  "auto" picks the
    smaller rendering — the bulk paths' choice, where bytes-on-wire
    beat encode CPU; "none"/"transpose" pin the filter (the stream path
    pins "none": REPLBATCH payloads already delta-encode their uuid
    columns, so the trial rarely pays there).  Under lzma the "auto"
    trial uses a cheap zlib-1 proxy so the expensive compressor runs
    once per chunk, on the chosen rendering."""
    if filt == "none":
        return FILT_NONE, _deflate(raw, level, alg)
    t8 = _transpose8(raw)
    if filt == "transpose":
        return FILT_TRANSPOSE8, _deflate(t8, level, alg)
    if alg == ALG_LZMA:
        if len(zlib.compress(t8, 1)) >= len(zlib.compress(raw, 1)):
            return FILT_NONE, _deflate(raw, level, alg)
        return FILT_TRANSPOSE8, _deflate(t8, level, alg)
    # zlib auto: the probe outputs ARE the final renderings — return
    # the winner instead of recompressing it identically
    zt = zlib.compress(t8, level)
    zr = zlib.compress(raw, level)
    if len(zt) < len(zr):
        return FILT_TRANSPOSE8, zt
    return FILT_NONE, zr


def _unfilter(data: bytes, filt: int) -> bytes:
    if filt == FILT_NONE:
        return data
    if filt == FILT_TRANSPOSE8:
        return _untranspose8(data)
    raise CompressFormatError(f"unknown chunk filter {filt}")


# ------------------------------------------------------------- one-shot

def compress_bytes(data: bytes, level: int = 1,
                   chunk: int = _DEFAULT_CHUNK,
                   filt: str = "none", alg: str = "zlib") -> bytes:
    """Frame `data` as one container (REPLBATCH payload compression)."""
    alg_tag = _alg_tag(alg)
    out = bytearray(MAGIC)
    out.append(alg_tag)
    mv = memoryview(data)
    for lo in range(0, len(mv), chunk):
        raw = bytes(mv[lo:lo + chunk])
        tag, comp = _filter_chunk(raw, level, filt, alg_tag)
        out += len(comp).to_bytes(4, "little")
        out.append(tag)
        out += len(raw).to_bytes(4, "little")
        out += zlib.crc32(raw).to_bytes(4, "little")
        out += comp
    out += (0).to_bytes(4, "little")
    return bytes(out)


def decompress_bytes(data: bytes, max_raw: int = 1 << 31) -> bytes:
    """Validate + inflate one container.  Raises CompressFormatError on
    ANY defect — the caller either gets the exact original bytes or an
    error, never a prefix.  One validation implementation for both
    transports: this is DecompressReader over a memory file plus the
    whole-buffer trailing-bytes check streams cannot make."""
    import io
    f = io.BytesIO(data)
    out = DecompressReader(f, max_raw=max_raw).read()
    if f.read(1):
        raise CompressFormatError("trailing bytes after container end")
    return out


def _inflate(comp: bytes, raw_len: int, alg: int = ALG_ZLIB) -> bytes:
    if alg == ALG_LZMA:
        try:
            d = lzma.LZMADecompressor()
            raw = d.decompress(comp, max_length=raw_len)
            if not d.eof or d.unused_data or len(raw) != raw_len:
                raise CompressFormatError("chunk lzma stream "
                                          "truncated/oversized")
            return raw
        except lzma.LZMAError as e:
            raise CompressFormatError(
                f"chunk inflate failed: {e}") from None
    try:
        d = zlib.decompressobj()
        raw = d.decompress(comp, raw_len)
        if d.unconsumed_tail or d.decompress(b"", 1):
            raise CompressFormatError("chunk inflates past its declared "
                                      "length")
        if not d.eof:
            raise CompressFormatError("chunk zlib stream truncated")
        if len(raw) != raw_len:
            raise CompressFormatError("chunk raw length mismatch")
        return raw
    except zlib.error as e:
        raise CompressFormatError(f"chunk inflate failed: {e}") from None


def is_compressed(head: bytes) -> bool:
    """Does `head` (>= 8 bytes) open a compressed container?"""
    return head[:len(MAGIC)] == MAGIC


# ------------------------------------------------------------- streaming

class CompressWriter:
    """File-object wrapper framing everything written through it.
    `write()` buffers to the chunk size, `finish()` flushes the tail and
    the end marker.  Presents only the `write` surface SnapshotWriter
    needs, so the snapshot container is this writer wrapped around the
    real file.  `filt="auto"` (the bulk default) picks the per-chunk
    filter by trial.  The working buffer is bounded by the chunk size —
    the shared-dump path registers that bound as a used_memory source
    while a compressed dump is in flight (persist/share.py)."""

    def __init__(self, f: IO[bytes], level: int = 1,
                 chunk: int = _DEFAULT_CHUNK, filt: str = "auto",
                 alg: str = "lzma"):
        self._f = f
        self._level = level
        self._chunk = chunk
        self._filt = filt
        self._alg = _alg_tag(alg)
        self._buf = bytearray()
        self.raw_bytes = 0
        f.write(MAGIC + bytes([self._alg]))

    def write(self, data: bytes) -> None:
        self._buf += data
        self.raw_bytes += len(data)
        while len(self._buf) >= self._chunk:
            self._emit(bytes(self._buf[:self._chunk]))
            del self._buf[:self._chunk]

    def _emit(self, raw: bytes) -> None:
        tag, comp = _filter_chunk(raw, self._level, self._filt,
                                  self._alg)
        head = len(comp).to_bytes(4, "little") + bytes([tag]) \
            + len(raw).to_bytes(4, "little") \
            + zlib.crc32(raw).to_bytes(4, "little")
        self._f.write(head + comp)

    def finish(self) -> None:
        if self._buf:
            self._emit(bytes(self._buf))
            self._buf.clear()
        self._f.write((0).to_bytes(4, "little"))


class DecompressReader:
    """File-object wrapper inflating a container incrementally with the
    same per-chunk validation as `decompress_bytes`.  `read(n)` returns
    exactly `n` bytes until the validated stream is exhausted — the
    surface SnapshotLoader consumes.  `head`: bytes the caller already
    consumed while sniffing the magic.  `max_raw` caps the cumulative
    inflated size (a corrupt length field must not OOM the consumer
    before validation catches up)."""

    def __init__(self, f: IO[bytes], head: bytes = b"",
                 max_raw: int = 1 << 62):
        self._f = f
        self._buf = bytearray()
        self._raw_total = 0
        self._max_raw = max_raw
        self._done = False
        need = _HEAD - len(head)
        head = head + (f.read(need) if need > 0 else b"")
        if len(head) < _HEAD or head[:len(MAGIC)] != MAGIC:
            raise CompressFormatError("bad compressed-container magic")
        self._alg = head[len(MAGIC)]
        _check_alg(self._alg)

    def _take(self, n: int) -> bytes:
        data = self._f.read(n)
        if len(data) != n:
            raise CompressFormatError("truncated compressed container")
        return data

    def _pump(self) -> bool:
        if self._done:
            return False
        comp_len = int.from_bytes(self._take(4), "little")
        if comp_len == 0:
            self._done = True
            return False
        filt = self._take(1)[0]
        raw_len = int.from_bytes(self._take(4), "little")
        crc = int.from_bytes(self._take(4), "little")
        if raw_len > _CHUNK_RAW or comp_len > _CHUNK_RAW + 1024:
            raise CompressFormatError("chunk lengths out of range")
        self._raw_total += raw_len
        if self._raw_total > self._max_raw:
            raise CompressFormatError("container exceeds the raw size cap")
        raw = _unfilter(_inflate(self._take(comp_len), raw_len,
                                 self._alg), filt)
        if zlib.crc32(raw) != crc:
            raise CompressFormatError("chunk crc mismatch")
        self._buf += raw
        return True

    def read(self, n: Optional[int] = None) -> bytes:
        if n is None:
            while self._pump():
                pass
            out = bytes(self._buf)
            self._buf.clear()
            return out
        while len(self._buf) < n and self._pump():
            pass
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out
