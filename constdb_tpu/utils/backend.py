"""Safe JAX backend probing.

The TPU device in this environment is attached through a tunnel
(sitecustomize registers the "axon" PJRT plugin via jax.config).  When the
device is healthy, backend init takes a few seconds; when it is wedged,
``import jax; jax.devices()`` HANGS indefinitely (round-1: bench.py died
rc=1 / the multichip dryrun timed out rc=124 on exactly this).  The
reference never faces this class of failure — its "device" is the host
allocator (reference src/lib.rs:63-78) — but a TPU-native build must treat
device attachment itself as a fallible dependency.

``probe_backend()`` initializes the backend in a THROWAWAY SUBPROCESS with
a timeout, so the caller learns {platform, device count} or a clear error
without ever risking its own process.  Callers then either proceed with
real init (probe said healthy) or force the CPU platform at the jax.config
level (the env var alone is overridden by sitecustomize).
"""

from __future__ import annotations

import os
import subprocess
import sys
from dataclasses import dataclass
from typing import Optional

_PROBE_SRC = (
    "import jax; d = jax.devices(); "
    "print(jax.default_backend(), len(d))"
)


@dataclass
class BackendProbe:
    ok: bool
    platform: str = ""
    n_devices: int = 0
    error: str = ""


_PROBE_MEMO: list = []  # [(BackendProbe, monotonic timestamp)]

# failed probes expire so a long-lived process can recover once a wedged
# device heals (round 5: the first probe timed out and the whole bench —
# and anything else in that process — was pinned to the CPU fallback
# forever); successful probes stay cached for the process lifetime
FAILED_PROBE_TTL = 300.0


def probe_backend(timeout: float = 90.0, cached: bool = True,
                  fail_ttl: Optional[float] = None) -> BackendProbe:
    """Report the default backend's platform/device count, never hanging.
    The (per-process) result is memoized by default: entry points that
    probe more than once on one boot (e.g. __graft_entry__ entry() +
    dryrun_multichip) pay a single subprocess init — and a wedged device
    a single timeout — not one per call.  Successful probes cache forever;
    FAILED probes only for `fail_ttl` seconds (default FAILED_PROBE_TTL,
    env CONSTDB_PROBE_FAIL_TTL), after which the next call re-probes."""
    import time as _time
    if fail_ttl is None:
        from ..conf import env_float
        fail_ttl = env_float("CONSTDB_PROBE_FAIL_TTL", FAILED_PROBE_TTL)
    if cached and _PROBE_MEMO:
        probe, ts = _PROBE_MEMO[0]
        if probe.ok or _time.monotonic() - ts < fail_ttl:
            return probe
    probe = _probe_backend_uncached(timeout)
    if cached:
        _PROBE_MEMO.clear()
        _PROBE_MEMO.append((probe, _time.monotonic()))
    return probe


def _probe_backend_uncached(timeout: float) -> BackendProbe:
    try:
        p = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                           capture_output=True, timeout=timeout, text=True)
    except subprocess.TimeoutExpired:
        return BackendProbe(False, error=f"backend init timed out "
                                         f"after {timeout:.0f}s (wedged device?)")
    except Exception as e:  # pragma: no cover - exotic spawn failures
        return BackendProbe(False, error=f"probe spawn failed: {e}")
    if p.returncode != 0:
        tail = (p.stderr or "").strip().splitlines()
        return BackendProbe(False, error=tail[-1] if tail else
                            f"probe exited rc={p.returncode}")
    try:
        platform, n = p.stdout.split()
        return BackendProbe(True, platform=platform, n_devices=int(n))
    except ValueError:
        return BackendProbe(False, error=f"unparsable probe output: "
                                         f"{p.stdout!r}")


def force_cpu_platform(n_devices: int = 1) -> None:
    """Pin this process to the CPU platform before any backend init.

    Must win over sitecustomize's plugin registration, hence the
    config-level override in addition to the env vars.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if n_devices > 1 and "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
