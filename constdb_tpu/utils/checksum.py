"""Streaming checksums for the snapshot format.

Capability parity with the reference's running CRC64 over snapshot bytes
(reference src/snapshot.rs:9-69 `SnapshotWriter.checksum_writter`,
src/snapshot.rs:207-214 validation on load).

Two interchangeable algorithms, tagged in the snapshot header so the loader
always verifies with the right one:
  * "crc64"     — CRC-64/XZ; C implementation in native/ (ctypes), with a
                  table-driven Python fallback.
  * "blake2b64" — 8-byte BLAKE2b via hashlib (C speed everywhere); used as the
                  default when the native library is not built.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
from typing import Optional

_POLY = 0xC96C5795D7870F42  # CRC-64/XZ, reflected

_TABLE: Optional[list[int]] = None


def _table() -> list[int]:
    global _TABLE
    if _TABLE is None:
        t = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ _POLY if crc & 1 else crc >> 1
            t.append(crc)
        _TABLE = t
    return _TABLE


def _crc64_py(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFFFFFFFFFF
    tab = _table()
    for b in data:
        crc = tab[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFFFFFFFFFF


_native = None


def _load_native():
    global _native
    if _native is not None:
        return _native
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for cand in (
        os.path.join(here, "_native", "libconstdb_native.so"),
        os.path.join(os.path.dirname(here), "native", "build", "libconstdb_native.so"),
    ):
        if os.path.exists(cand):
            try:
                lib = ctypes.CDLL(cand)
                lib.cst_crc64.restype = ctypes.c_uint64
                lib.cst_crc64.argtypes = [ctypes.c_uint64, ctypes.c_char_p, ctypes.c_size_t]
                _native = lib
                return lib
            except OSError:
                pass
    _native = False
    return False


def crc64(data, crc: int = 0) -> int:
    if not isinstance(data, bytes):
        data = bytes(data)
    lib = _load_native()
    if lib:
        return lib.cst_crc64(crc, data, len(data))
    return _crc64_py(data, crc)


class StreamChecksum:
    """Running checksum with an algorithm tag byte for the snapshot header."""

    ALG_CRC64 = 1
    ALG_BLAKE2B64 = 2

    def __init__(self, alg: Optional[int] = None):
        if alg is None:
            alg = self.ALG_CRC64 if _load_native() else self.ALG_BLAKE2B64
        self.alg = alg
        if alg == self.ALG_CRC64:
            self._crc = 0
            self._h = None
        elif alg == self.ALG_BLAKE2B64:
            self._h = hashlib.blake2b(digest_size=8)
        else:
            raise ValueError(f"unknown checksum algorithm {alg}")

    def update(self, data) -> None:
        if self.alg == self.ALG_CRC64:
            if not isinstance(data, bytes):
                data = bytes(data)
            self._crc = crc64(data, self._crc)
        else:
            self._h.update(data)

    def digest(self) -> int:
        if self.alg == self.ALG_CRC64:
            return self._crc
        return int.from_bytes(self._h.digest(), "big")
