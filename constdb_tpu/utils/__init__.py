from .bytesutil import bytes2i64, bytes2u64, i64_to_bytes
from .hlc import HLC, uuid_ms, uuid_seq, now_ms, now_secs
from .varint import write_uvarint, write_varint, read_uvarint, read_varint, VarintReader
from .checksum import StreamChecksum, crc64

__all__ = [
    "bytes2i64", "bytes2u64", "i64_to_bytes",
    "HLC", "uuid_ms", "uuid_seq", "now_ms", "now_secs",
    "write_uvarint", "write_varint", "read_uvarint", "read_varint", "VarintReader",
    "StreamChecksum", "crc64",
]
