"""Staging index tables: native C++, three binding tiers.

The merge hot path resolves millions of (bytes -> id) and (int64 -> int64)
probes per batch; native/tables.cpp does them in C with BATCH entry points
so Python crosses the FFI boundary once per column, not once per row.

Binding tiers, best available wins:
  1. CPython extension (native/pyext.cpp, `cst_ext`) — walks bytes lists
     directly in C, no Python-side blob packing at all;
  2. ctypes over libconstdb_native.so — caller packs a blob + offsets;
  3. pure Python dicts — keeps everything working on a fresh checkout
     before `make -C native`, at dict speed.

API shape is numpy-first: batch methods take/return int64 arrays.
"""

from __future__ import annotations

import ctypes
import importlib.util
import os
from typing import Optional

import numpy as np

_I64 = np.int64

_lib = None
_ext = None


def nonnull_mask(items: list):
    """Bool ndarray marking entries that are not None — C-speed when the
    extension is built (the per-row generator over multi-million-row
    value columns is a top merge-dispatch cost), pure-Python otherwise."""
    import numpy as np
    ext = load_ext()
    # exact-list gate mirrors the C side's PyList_CheckExact: other
    # sized iterables must take the same (pure) path on BOTH tiers
    if type(items) is list and ext is not None and \
            hasattr(ext, "nonnull_mask"):
        return np.frombuffer(ext.nonnull_mask(items), dtype=bool)
    return np.fromiter((v is not None for v in items), dtype=bool,
                       count=len(items))


_ABI_STAMP_CACHE: list = []


def expected_abi_stamp() -> Optional[str]:
    """sha256 over the sorted native/*.cpp sources — the same hash the
    Makefile compiles into cst_ext.so as CST_ABI_STAMP (native/Makefile
    $(STAMP) rule: `cat $(sort $(wildcard *.cpp)) | sha256sum`).  The
    extension and serve.py share frozen row layouts (opcode numbering,
    payload shapes); a .so built from different sources could emit rows
    the Python side misreads, so load_ext compares this against the
    module's own abi_stamp() and refuses a mismatch.  None when the
    source tree is absent (artifact-only deployments have nothing to
    compare against — the shipped .so is trusted as-is)."""
    if not _ABI_STAMP_CACHE:
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        src = os.path.join(os.path.dirname(here), "native")
        try:
            names = sorted(n for n in os.listdir(src) if n.endswith(".cpp"))
        except OSError:
            names = []
        if not names:
            _ABI_STAMP_CACHE.append(None)
        else:
            import hashlib
            h = hashlib.sha256()
            for n in names:
                with open(os.path.join(src, n), "rb") as f:
                    h.update(f.read())
            _ABI_STAMP_CACHE.append(h.hexdigest())
    return _ABI_STAMP_CACHE[0]


def load_ext():
    """The CPython extension module, or None.  CONSTDB_NO_NATIVE=1 forces
    the pure-Python tiers (A/B floor measurement — opbench.py).  A .so
    whose compiled-in ABI stamp does not match the native/*.cpp sources
    on disk is refused LOUDLY (stale build: its row layouts may disagree
    with what serve.py expects) — rebuild with `make -C native`, or let
    bench.py's ensure_native (CONSTDB_AUTO_NATIVE, default on) do it."""
    global _ext
    from ..conf import env_str
    if env_str("CONSTDB_NO_NATIVE"):
        return None
    if _ext is not None:
        return _ext or None
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for cand in (
        os.path.join(here, "_native", "cst_ext.so"),
        os.path.join(os.path.dirname(here), "native", "build", "cst_ext.so"),
    ):
        if os.path.exists(cand):
            try:
                spec = importlib.util.spec_from_file_location("cst_ext", cand)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
            except (ImportError, OSError):
                continue
            want = expected_abi_stamp()
            got = getattr(mod, "abi_stamp", lambda: "")()
            if want is not None and got != want:
                import logging
                logging.getLogger("constdb.native").warning(
                    "stale cst_ext.so at %s (abi stamp %s != sources %s): "
                    "refusing to load it — rebuild with `make -C native` "
                    "(bench.py ensure_native rebuilds automatically unless "
                    "CONSTDB_AUTO_NATIVE=0)",
                    cand, (got or "<unstamped>")[:12], want[:12])
                continue
            _ext = mod
            return mod
    _ext = False
    return None


def reload_tiers() -> bool:
    """Forget the (possibly negative) loader caches and retry — the public
    hook for callers that build the native artifacts at runtime (bench.py
    ensure_native).  Returns True when the CPython extension loads."""
    global _ext, _lib
    _ext = None
    _lib = None
    _ABI_STAMP_CACHE.clear()
    return load_ext() is not None


def load_native() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib or None
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for cand in (
        os.path.join(here, "_native", "libconstdb_native.so"),
        os.path.join(os.path.dirname(here), "native", "build",
                     "libconstdb_native.so"),
    ):
        if os.path.exists(cand):
            try:
                lib = ctypes.CDLL(cand)
                _bind(lib)
                _lib = lib
                return lib
            except (OSError, AttributeError):
                continue
    _lib = False
    return None


def _bind(lib: ctypes.CDLL) -> None:
    c = ctypes
    P8 = c.POINTER(c.c_uint8)
    P64 = c.POINTER(c.c_int64)
    sigs = {
        "cst_strtab_new": (c.c_void_p, [c.c_int64]),
        "cst_strtab_free": (None, [c.c_void_p]),
        "cst_strtab_len": (c.c_int64, [c.c_void_p]),
        "cst_strtab_get_or_insert": (c.c_int64, [c.c_void_p, P8, c.c_int64]),
        "cst_strtab_lookup": (c.c_int64, [c.c_void_p, P8, c.c_int64]),
        "cst_strtab_get_or_insert_batch":
            (c.c_int64, [c.c_void_p, P8, P64, c.c_int64, P64]),
        "cst_strtab_lookup_batch": (None, [c.c_void_p, P8, P64, c.c_int64, P64]),
        "cst_strtab_bytes_len": (c.c_int64, [c.c_void_p, c.c_int64]),
        "cst_strtab_bytes_get": (None, [c.c_void_p, c.c_int64, P8]),
        "cst_i64_new": (c.c_void_p, [c.c_int64]),
        "cst_i64_free": (None, [c.c_void_p]),
        "cst_i64_len": (c.c_int64, [c.c_void_p]),
        "cst_i64_get": (c.c_int64, [c.c_void_p, c.c_int64, c.c_int64]),
        "cst_i64_put": (None, [c.c_void_p, c.c_int64, c.c_int64]),
        "cst_i64_del": (c.c_int64, [c.c_void_p, c.c_int64, c.c_int64]),
        "cst_i64_lookup_batch": (None, [c.c_void_p, P64, c.c_int64, c.c_int64, P64]),
        "cst_i64_put_batch": (None, [c.c_void_p, P64, P64, c.c_int64]),
        "cst_i64_get_or_assign_batch":
            (c.c_int64, [c.c_void_p, P64, c.c_int64, c.c_int64, P64]),
    }
    for name, (res, args) in sigs.items():
        fn = getattr(lib, name)
        fn.restype = res
        fn.argtypes = args


def _as_i64_ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _as_u8_ptr(buf):
    return ctypes.cast(ctypes.c_char_p(bytes(buf) if not isinstance(buf, bytes)
                                       else buf),
                       ctypes.POINTER(ctypes.c_uint8))


def pack_bytes_list(items: list) -> tuple[bytes, np.ndarray]:
    """-> (blob, offs[n+1]) for batch string calls."""
    lens = np.fromiter((len(b) for b in items), dtype=_I64, count=len(items))
    offs = np.zeros(len(items) + 1, dtype=_I64)
    np.cumsum(lens, out=offs[1:])
    return b"".join(items), offs


# ----------------------------------------------------------------- StrTable

class _NativeStrTable:
    """bytes -> dense id, insertion-ordered."""

    __slots__ = ("_h", "_lib")

    def __init__(self, cap_hint: int = 16):
        self._lib = load_native()
        self._h = self._lib.cst_strtab_new(cap_hint)

    def __len__(self) -> int:
        return self._lib.cst_strtab_len(self._h)

    def __del__(self):
        try:
            self._lib.cst_strtab_free(self._h)
        except (AttributeError, TypeError):
            pass

    def get_or_insert(self, b: bytes) -> int:
        return self._lib.cst_strtab_get_or_insert(self._h, _as_u8_ptr(b), len(b))

    def lookup(self, b: bytes) -> int:
        return self._lib.cst_strtab_lookup(self._h, _as_u8_ptr(b), len(b))

    def get_or_insert_batch(self, items: list) -> tuple[np.ndarray, int]:
        """-> (ids[n], n_new).  New ids are sequential from the previous
        table size, in first-occurrence order."""
        blob, offs = pack_bytes_list(items)
        out = np.empty(len(items), dtype=_I64)
        n_new = self._lib.cst_strtab_get_or_insert_batch(
            self._h, _as_u8_ptr(blob), _as_i64_ptr(offs), len(items),
            _as_i64_ptr(out))
        return out, int(n_new)

    def lookup_batch(self, items: list) -> np.ndarray:
        blob, offs = pack_bytes_list(items)
        out = np.empty(len(items), dtype=_I64)
        self._lib.cst_strtab_lookup_batch(
            self._h, _as_u8_ptr(blob), _as_i64_ptr(offs), len(items),
            _as_i64_ptr(out))
        return out

    def bytes_of(self, idx: int) -> bytes:
        n = self._lib.cst_strtab_bytes_len(self._h, idx)
        if n < 0:
            raise IndexError(idx)
        buf = ctypes.create_string_buffer(n)
        self._lib.cst_strtab_bytes_get(
            self._h, idx, ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8)))
        return buf.raw


class _PyStrTable:
    __slots__ = ("_d", "_items")

    def __init__(self, cap_hint: int = 16):
        self._d: dict[bytes, int] = {}
        self._items: list[bytes] = []

    def __len__(self) -> int:
        return len(self._d)

    def get_or_insert(self, b: bytes) -> int:
        i = self._d.get(b, -1)
        if i < 0:
            i = len(self._items)
            self._d[b] = i
            self._items.append(b)
        return i

    def lookup(self, b: bytes) -> int:
        return self._d.get(b, -1)

    def get_or_insert_batch(self, items: list) -> tuple[np.ndarray, int]:
        before = len(self._items)
        gi = self.get_or_insert
        out = np.fromiter((gi(b) for b in items), dtype=_I64, count=len(items))
        return out, len(self._items) - before

    def lookup_batch(self, items: list) -> np.ndarray:
        g = self._d.get
        return np.fromiter((g(b, -1) for b in items), dtype=_I64,
                           count=len(items))

    def bytes_of(self, idx: int) -> bytes:
        return self._items[idx]


# ----------------------------------------------------------------- I64Dict

class _NativeI64Dict:
    """int64 -> int64 with batch ops and deletion."""

    __slots__ = ("_h", "_lib")

    def __init__(self, cap_hint: int = 16):
        self._lib = load_native()
        self._h = self._lib.cst_i64_new(cap_hint)

    def __len__(self) -> int:
        return self._lib.cst_i64_len(self._h)

    def __del__(self):
        try:
            self._lib.cst_i64_free(self._h)
        except (AttributeError, TypeError):
            pass

    def get(self, k: int, dflt: int = -1) -> int:
        return self._lib.cst_i64_get(self._h, k, dflt)

    def put(self, k: int, v: int) -> None:
        self._lib.cst_i64_put(self._h, k, v)

    def delete(self, k: int, dflt: int = -1) -> int:
        return self._lib.cst_i64_del(self._h, k, dflt)

    def lookup_batch(self, keys: np.ndarray, dflt: int = -1) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=_I64)
        out = np.empty(len(keys), dtype=_I64)
        self._lib.cst_i64_lookup_batch(self._h, _as_i64_ptr(keys), len(keys),
                                       dflt, _as_i64_ptr(out))
        return out

    def put_batch(self, keys: np.ndarray, vals: np.ndarray) -> None:
        keys = np.ascontiguousarray(keys, dtype=_I64)
        vals = np.ascontiguousarray(vals, dtype=_I64)
        self._lib.cst_i64_put_batch(self._h, _as_i64_ptr(keys),
                                    _as_i64_ptr(vals), len(keys))

    def get_or_assign_batch(self, keys: np.ndarray, next_val: int
                            ) -> tuple[np.ndarray, int]:
        """Missing keys get sequential values from next_val (first-occurrence
        order).  -> (vals[n], n_new)."""
        keys = np.ascontiguousarray(keys, dtype=_I64)
        out = np.empty(len(keys), dtype=_I64)
        n_new = self._lib.cst_i64_get_or_assign_batch(
            self._h, _as_i64_ptr(keys), len(keys), next_val, _as_i64_ptr(out))
        return out, int(n_new)


class _PyI64Dict:
    __slots__ = ("_d",)

    def __init__(self, cap_hint: int = 16):
        self._d: dict[int, int] = {}

    def __len__(self) -> int:
        return len(self._d)

    def get(self, k: int, dflt: int = -1) -> int:
        return self._d.get(k, dflt)

    def put(self, k: int, v: int) -> None:
        self._d[k] = v

    def delete(self, k: int, dflt: int = -1) -> int:
        return self._d.pop(k, dflt)

    def lookup_batch(self, keys: np.ndarray, dflt: int = -1) -> np.ndarray:
        g = self._d.get
        return np.fromiter((g(k, dflt) for k in keys.tolist()), dtype=_I64,
                           count=len(keys))

    def put_batch(self, keys: np.ndarray, vals: np.ndarray) -> None:
        self._d.update(zip(keys.tolist(), vals.tolist()))

    def get_or_assign_batch(self, keys: np.ndarray, next_val: int
                            ) -> tuple[np.ndarray, int]:
        d = self._d
        out = np.empty(len(keys), dtype=_I64)
        start = next_val
        for i, k in enumerate(keys.tolist()):
            v = d.get(k)
            if v is None:
                v = next_val
                d[k] = v
                next_val += 1
            out[i] = v
        return out, next_val - start


# ------------------------------------------------- CPython-extension tier

class _ExtStrTable:
    """bytes -> dense id via the C extension (no blob packing)."""

    __slots__ = ("_h", "_m")

    def __init__(self, cap_hint: int = 16):
        self._m = load_ext()
        self._h = self._m.strtab_new(cap_hint)

    def __len__(self) -> int:
        return self._m.strtab_len(self._h)

    def get_or_insert(self, b: bytes) -> int:
        return self._m.strtab_get_or_insert(self._h, b)

    def lookup(self, b: bytes) -> int:
        return self._m.strtab_lookup(self._h, b)

    def get_or_insert_batch(self, items: list) -> tuple[np.ndarray, int]:
        out = np.empty(len(items), dtype=_I64)
        n_new = self._m.strtab_get_or_insert_batch(self._h, items, out)
        return out, n_new

    def lookup_batch(self, items: list) -> np.ndarray:
        out = np.empty(len(items), dtype=_I64)
        self._m.strtab_lookup_batch(self._h, items, out)
        return out

    def bytes_of(self, idx: int) -> bytes:
        return self._m.strtab_bytes_of(self._h, idx)


class _ExtI64Dict:
    __slots__ = ("_h", "_m")

    def __init__(self, cap_hint: int = 16):
        self._m = load_ext()
        self._h = self._m.i64_new(cap_hint)

    def __len__(self) -> int:
        return self._m.i64_len(self._h)

    def get(self, k: int, dflt: int = -1) -> int:
        return self._m.i64_get(self._h, k, dflt)

    def put(self, k: int, v: int) -> None:
        self._m.i64_put(self._h, k, v)

    def delete(self, k: int, dflt: int = -1) -> int:
        return self._m.i64_del(self._h, k, dflt)

    def lookup_batch(self, keys: np.ndarray, dflt: int = -1) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=_I64)
        out = np.empty(len(keys), dtype=_I64)
        self._m.i64_lookup_batch(self._h, keys, dflt, out)
        return out

    def put_batch(self, keys: np.ndarray, vals: np.ndarray) -> None:
        keys = np.ascontiguousarray(keys, dtype=_I64)
        vals = np.ascontiguousarray(vals, dtype=_I64)
        self._m.i64_put_batch(self._h, keys, vals)

    def get_or_assign_batch(self, keys: np.ndarray, next_val: int
                            ) -> tuple[np.ndarray, int]:
        keys = np.ascontiguousarray(keys, dtype=_I64)
        out = np.empty(len(keys), dtype=_I64)
        n_new = self._m.i64_get_or_assign_batch(self._h, keys, next_val, out)
        return out, n_new


def StrTable(cap_hint: int = 16):
    if load_ext():
        return _ExtStrTable(cap_hint)
    return (_NativeStrTable if load_native() else _PyStrTable)(cap_hint)


def I64Dict(cap_hint: int = 16):
    if load_ext():
        return _ExtI64Dict(cap_hint)
    return (_NativeI64Dict if load_native() else _PyI64Dict)(cap_hint)
