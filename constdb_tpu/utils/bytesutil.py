"""Byte-string helpers.

Capability parity with reference src/lib/utils.rs:3-61 (`bytes2i64`/`bytes2u64`)
and src/resp.rs:12-27 (interned int→bytes cache).
"""

from __future__ import annotations

from typing import Optional

# Interned encodings for small integers: the hot path for RESP integer replies.
_INT_CACHE_LO, _INT_CACHE_HI = -1, 10000
_INT_CACHE = [str(i).encode() for i in range(_INT_CACHE_LO, _INT_CACHE_HI)]


def i64_to_bytes(n: int) -> bytes:
    if _INT_CACHE_LO <= n < _INT_CACHE_HI:
        return _INT_CACHE[n - _INT_CACHE_LO]
    return str(n).encode()


def bytes2i64(b: bytes) -> Optional[int]:
    """ASCII → signed 64-bit int; None when not a canonical integer."""
    if not b:
        return None
    try:
        v = int(b)
    except ValueError:
        return None
    # Reject non-canonical forms ("+1", " 1", "01") like a strict ASCII parser.
    if str(v).encode() != b:
        return None
    if not (-(1 << 63) <= v < (1 << 63)):
        return None
    return v


def bytes2u64(b: bytes) -> Optional[int]:
    if not b:
        return None
    try:
        v = int(b)
    except ValueError:
        return None
    if str(v).encode() != b or not (0 <= v < (1 << 64)):
        return None
    return v
