"""Executable entry points (reference bin/: server.rs, cli.rs, test.rs)."""
