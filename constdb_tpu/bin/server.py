"""constdb-tpu-server: run one node.

Capability parity with the reference server binary (reference bin/server.rs
→ lib.rs `run_server`): config, logging, bind, cron, serve until signalled.
Background snapshot dumps replace the reference's fork()-COW scheme with the
capture-on-loop / encode-on-thread pipeline (persist/snapshot.py), and the
snapshot is reloaded on boot — the reference restarts empty (SURVEY.md §5.4).

Usage: python -m constdb_tpu.bin.server [config.toml] [--port N] ...
"""

from __future__ import annotations

import asyncio
import logging
import signal
import sys

from ..conf import Config, build_engine, load_config
from ..persist.snapshot import NodeMeta, dump_keyspace
from ..server.io import ServerApp, start_node
from ..server.node import Node

log = logging.getLogger("constdb_tpu.server")


def setup_logging(cfg: Config) -> None:
    level = getattr(logging, cfg.log_level.upper(), logging.INFO)
    fmt = "%(asctime)s %(levelname)s %(filename)s:%(lineno)d - %(message)s"
    if cfg.log and cfg.log != "console":
        # size-capped rolling file (reference src/lib.rs:109-136 rolls its
        # log by size too)
        from logging.handlers import RotatingFileHandler
        handler = RotatingFileHandler(cfg.log, maxBytes=cfg.log_max_bytes,
                                      backupCount=cfg.log_backups)
        handler.setFormatter(logging.Formatter(fmt))
        logging.basicConfig(level=level, handlers=[handler])
    else:
        logging.basicConfig(level=level, format=fmt)


def daemonize(cfg: Config) -> str:
    """Detach (double fork + setsid), point stdio at /dev/null, and write
    the pid file (reference src/lib.rs:89-108).  Returns the pid path."""
    import os

    if os.fork() > 0:
        os._exit(0)
    os.setsid()
    if os.fork() > 0:
        os._exit(0)
    devnull = os.open(os.devnull, os.O_RDWR)
    for fd in (0, 1, 2):
        os.dup2(devnull, fd)
    os.close(devnull)
    pid_path = cfg.pid_file or os.path.join(cfg.work_dir, "constdb.pid")
    os.makedirs(cfg.work_dir, exist_ok=True)
    with open(pid_path, "w") as f:
        f.write(str(os.getpid()))
    return pid_path


def _snapshot_fsync() -> bool:
    """Durable dumps by default (file data + parent directory entry —
    persist/snapshot.py): CONSTDB_SNAPSHOT_FSYNC=0 trades the crash
    guarantee for dump latency."""
    from ..conf import env_flag
    return env_flag("CONSTDB_SNAPSHOT_FSYNC", True)


def _dump_container_level(app: ServerApp) -> int:
    """Background/shutdown dumps ride the compressed snapshot container
    (persist/snapshot.py; boot restore sniffs the magic, pre-PR files
    stay loadable).  Gates on the same per-app/env compression master
    switch as every wire decision (CONSTDB_WIRE_COMPRESS=0 or
    ServerApp(wire_compress=False) keeps dumps in the plain pre-PR
    format)."""
    from ..replica.link import wire_compress_of
    return 6 if wire_compress_of(app) else 0


async def snapshot_cron(app: ServerApp, cfg: Config) -> None:
    """Periodic background dump (fork-free; see persist/snapshot.py)."""
    from ..engine.base import batch_from_keyspace
    from ..persist.snapshot import write_snapshot_file

    while True:
        await asyncio.sleep(cfg.snapshot_interval)
        node = app.node
        # RuntimeError: a sharded node's dump awaits serve-pool worker
        # exports, and a failed worker surfaces as one — it must not
        # kill the cron (the node would silently never snapshot again)
        try:
            if node.serve_plane is not None:
                # shard-per-core node: the workers hold the state —
                # dump their consolidated exports (landed watermark: a
                # dump may not claim coverage of minted-but-in-flight
                # writes)
                await _dump_plane_snapshot(app, cfg)
            else:
                node.ensure_flushed()  # device-resident merge → host
                capture = batch_from_keyspace(node.ks)  # on the loop
                meta = NodeMeta(node_id=node.node_id, alias=node.alias,
                                addr=app.advertised_addr,
                                repl_last_uuid=node.repl_log.last_uuid)
                records = node.replicas.records()
                await asyncio.to_thread(
                    write_snapshot_file, cfg.snapshot_path, meta,
                    records, [capture],
                    chunk_keys=cfg.snapshot_chunk_keys,
                    compress_level=cfg.snapshot_compress_level,
                    fsync=_snapshot_fsync(),
                    container_level=_dump_container_level(app))
            log.info("background snapshot written to %s",
                     cfg.snapshot_path)
        except (OSError, RuntimeError) as e:
            log.error("background snapshot failed: %s", e)


async def amain(cfg: Config) -> None:
    node = Node(node_id=cfg.node_id, alias=cfg.node_alias,
                engine=build_engine(cfg.engine),
                repl_log_cap=cfg.repl_log_cap)
    app = await start_node(
        node, host=cfg.ip, port=cfg.port,
        advertised_addr=cfg.addr, work_dir=cfg.work_dir,
        heartbeat=float(cfg.replica_heartbeat_frequency),
        reconnect_delay=float(cfg.replica_gossip_frequency) / 3.0,
        snapshot_chunk_keys=cfg.snapshot_chunk_keys,
        snapshot_compress_level=cfg.snapshot_compress_level,
        snapshot_path=cfg.snapshot_path,
        tcp_backlog=cfg.tcp_backlog,
        gc_peer_retention=float(cfg.gc_peer_retention),
        ingest_shards=cfg.ingest_shards,
        ingest_shard_min_bytes=cfg.ingest_shard_min_bytes,
        serve_shards=cfg.serve_shards or None,
        aof=cfg.aof or None,
        aof_fsync=cfg.aof_fsync or None,
        aof_rewrite_pct=cfg.aof_rewrite_pct
        if cfg.aof_rewrite_pct >= 0 else None,
        aof_dir=cfg.aof_dir,
        cluster_group=cfg.cluster_group,
        restore_to=cfg.restore_to)
    log.info("constdb-tpu node %d (engine=%s) serving on %s",
             node.node_id, node.engine.name, app.advertised_addr)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    crons = []
    if cfg.snapshot_interval > 0 and cfg.snapshot_path:
        crons.append(asyncio.create_task(snapshot_cron(app, cfg)))
    await stop.wait()
    for t in crons:
        t.cancel()
    if cfg.snapshot_path:
        # final synchronous dump so a clean restart resumes warm
        if node.serve_plane is not None:
            # shard-per-core node: consolidate the worker shards — the
            # parent keyspace is empty by design (server/serve_shards.py)
            await _dump_plane_snapshot(app, cfg)
        else:
            node.ensure_flushed()  # device-resident merge state → host
            dump_keyspace(cfg.snapshot_path, node.ks,
                          NodeMeta(node_id=node.node_id, alias=node.alias,
                                   addr=app.advertised_addr,
                                   repl_last_uuid=node.repl_log.last_uuid),
                          node.replicas.records(),
                          chunk_keys=cfg.snapshot_chunk_keys,
                          compress_level=cfg.snapshot_compress_level,
                          fsync=_snapshot_fsync(),
                          container_level=_dump_container_level(app))
        log.info("final snapshot written to %s", cfg.snapshot_path)
    await app.close()


async def _dump_plane_snapshot(app: ServerApp, cfg: Config) -> None:
    """Whole-state dump of a sharded serving node: worker exports,
    landed watermark (the same rules as snapshot_cron / share.py)."""
    from ..persist.snapshot import write_snapshot_file

    node = app.node
    # watermarks (own repl_last AND the per-peer records) are captured
    # BEFORE the worker exports: frames landing mid-export end up in the
    # state but above every recorded watermark (harmless redelivery).
    # Captured after, a record would claim pull coverage the exported
    # state lacks, and a boot restore adopting it would skip those
    # frames' redelivery forever (persist/share.py has the long form).
    repl_last = node.repl_log.landed_last_uuid
    records = node.replicas.records()
    captures = await node.serve_plane.export_batches()
    meta = NodeMeta(node_id=node.node_id, alias=node.alias,
                    addr=app.advertised_addr, repl_last_uuid=repl_last)
    await asyncio.to_thread(
        write_snapshot_file, cfg.snapshot_path, meta,
        records, captures,
        chunk_keys=cfg.snapshot_chunk_keys,
        compress_level=cfg.snapshot_compress_level,
        fsync=_snapshot_fsync(),
        container_level=_dump_container_level(app))


def main(argv=None) -> None:
    import os

    cfg = load_config(argv)
    pid_path = ""
    if cfg.daemon:
        if not cfg.log or cfg.log == "console":
            # stdio points at /dev/null after detaching — console logging
            # would be silently discarded, so force a file
            cfg.log = os.path.join(cfg.work_dir, "constdb.log")
        pid_path = daemonize(cfg)
    setup_logging(cfg)
    try:
        asyncio.run(amain(cfg))
    except KeyboardInterrupt:
        pass
    finally:
        if pid_path:
            import os
            try:
                os.unlink(pid_path)
            except OSError:
                pass


if __name__ == "__main__":
    main(sys.argv[1:])
