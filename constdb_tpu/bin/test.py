"""constdb-tpu-test: black-box convergence harness against LIVE servers.

Capability parity with the reference's integration binary (reference
bin/test.rs:16-437, SURVEY.md §4): connect to ≥3 running nodes as a client,
form the mesh with MEET, then drive randomized concurrent workloads with a
local oracle model and assert convergence.  Unlike the reference it polls
for convergence (DESC-based state compare) instead of sleeping fixed
durations.

Usage:
  python -m constdb_tpu.bin.server --port 9001 &
  python -m constdb_tpu.bin.server --port 9002 &
  python -m constdb_tpu.bin.server --port 9003 &
  python -m constdb_tpu.bin.test --replicas 127.0.0.1:9001 \
      127.0.0.1:9002 127.0.0.1:9003
"""

from __future__ import annotations

import argparse
import asyncio
import random
import sys
import time

from ..resp.codec import make_parser, encode_msg
from ..resp.message import Arr, Bulk, Err, Int, Msg, Nil


class Conn:
    def __init__(self) -> None:
        self.reader = None
        self.writer = None
        self.parser = make_parser()

    async def connect(self, addr: str) -> "Conn":
        host, port = addr.rsplit(":", 1)
        self.reader, self.writer = await asyncio.open_connection(host, int(port))
        return self

    async def cmd(self, *parts) -> Msg:
        items = [Bulk(p if isinstance(p, bytes) else str(p).encode())
                 for p in parts]
        self.writer.write(encode_msg(Arr(items)))
        await self.writer.drain()
        while (m := self.parser.next_msg()) is None:
            data = await self.reader.read(1 << 16)
            if not data:
                raise ConnectionError("EOF")
            self.parser.feed(data)
        if isinstance(m, Err):
            raise RuntimeError(m.val.decode())
        return m


async def poll_equal(conns, probe, timeout: float = 30.0):
    """Wait until `probe(conn)` returns the same value on every node."""
    deadline = time.monotonic() + timeout
    while True:
        vals = [await probe(c) for c in conns]
        if all(repr(v) == repr(vals[0]) for v in vals[1:]):
            return vals[0]
        if time.monotonic() > deadline:
            raise AssertionError(f"no convergence: {vals}")
        await asyncio.sleep(0.25)


async def test_counters(conns, rng, n_ops):
    oracle = 0
    for _ in range(n_ops):
        c = rng.choice(conns)
        if rng.random() < 0.5:
            await c.cmd("incr", "t:cnt")
            oracle += 1
        else:
            await c.cmd("decr", "t:cnt")
            oracle -= 1
    got = await poll_equal(conns, lambda c: c.cmd("get", "t:cnt"))
    assert got == Int(oracle), f"counter oracle {oracle} != {got}"
    print(f"  counters: {n_ops} ops -> {oracle} on all nodes ✓")


async def test_bytes(conns, rng, n_ops):
    keys = [f"t:b{i}" for i in range(5)]
    for _ in range(n_ops):
        c = rng.choice(conns)
        k = rng.choice(keys)
        if rng.random() < 0.85:
            await c.cmd("set", k, f"v{rng.randrange(10_000)}")
        else:
            await c.cmd("del", k)
        await asyncio.sleep(0.002)  # ms-spaced: program order == LWW order
    for k in keys:
        await poll_equal(conns, lambda c, k=k: c.cmd("get", k))
    print(f"  bytes: {n_ops} ops converged on {len(keys)} keys ✓")


async def test_set(conns, rng, n_ops):
    members = [f"m{i}" for i in range(16)]
    oracle: set[bytes] = set()
    for _ in range(n_ops):
        c = rng.choice(conns)
        m = rng.choice(members)
        if rng.random() < 0.65:
            await c.cmd("sadd", "t:s", m)
            oracle.add(m.encode())
        else:
            await c.cmd("srem", "t:s", m)
            oracle.discard(m.encode())
        await asyncio.sleep(0.002)

    async def probe(c):
        got = await c.cmd("smembers", "t:s")
        return sorted(i.val for i in got.items) if isinstance(got, Arr) else got

    got = await poll_equal(conns, probe)
    assert got == sorted(oracle), f"set oracle mismatch: {got} != {sorted(oracle)}"
    print(f"  set: {n_ops} ops, {len(oracle)} members on all nodes ✓")


async def test_dict(conns, rng, n_ops):
    fields = [f"f{i}" for i in range(12)]
    oracle: dict[bytes, bytes] = {}
    for _ in range(n_ops):
        c = rng.choice(conns)
        f = rng.choice(fields)
        if rng.random() < 0.7:
            v = f"v{rng.randrange(10_000)}"
            await c.cmd("hset", "t:h", f, v)
            oracle[f.encode()] = v.encode()
        else:
            await c.cmd("hdel", "t:h", f)
            oracle.pop(f.encode(), None)
        await asyncio.sleep(0.002)

    async def probe(c):
        got = await c.cmd("hgetall", "t:h")
        if not isinstance(got, Arr):
            return got
        return sorted((kv.items[0].val, kv.items[1].val) for kv in got.items)

    got = await poll_equal(conns, probe)
    assert got == sorted(oracle.items()), "dict oracle mismatch"
    print(f"  dict: {n_ops} ops, {len(oracle)} fields on all nodes ✓")


async def bench_ops(addr: str, n_reqs: int, pipeline: int,
                    n_conns: int) -> dict:
    """redis-benchmark-style pipelined op-path throughput against ONE node
    (the evidence behind the reference's qualitative "much efficient" IO
    claim, README.md:12).  -> {cmd: ops_per_sec}."""
    results = {}
    val = b"x" * 32

    def encode(kind: bytes, i: int) -> bytes:
        key = b"bench:%d" % (i % 1000)
        if kind == b"set":
            return encode_msg(Arr([Bulk(b"set"), Bulk(key), Bulk(val)]))
        if kind == b"get":
            return encode_msg(Arr([Bulk(b"get"), Bulk(key)]))
        return encode_msg(Arr([Bulk(b"incr"), Bulk(b"bench:cnt:%d" % (i % 16))]))

    async def worker(conn: Conn, kind: bytes, n: int) -> None:
        sent = 0
        while sent < n:
            burst = min(pipeline, n - sent)
            buf = bytearray()
            for i in range(sent, sent + burst):
                buf += encode(kind, i)
            conn.writer.write(bytes(buf))
            await conn.writer.drain()
            got = 0
            while got < burst:
                m = conn.parser.next_msg()
                if m is not None:
                    got += 1
                    continue
                data = await conn.reader.read(1 << 16)
                if not data:
                    raise ConnectionError("EOF")
                conn.parser.feed(data)
            sent += burst

    for kind in (b"set", b"get", b"incr"):
        conns = [await Conn().connect(addr) for _ in range(n_conns)]
        per = n_reqs // n_conns
        t0 = time.perf_counter()
        await asyncio.gather(*(worker(c, kind, per) for c in conns))
        dt = time.perf_counter() - t0
        ops = per * n_conns / dt
        results[kind.decode()] = int(ops)
        print(f"  {kind.decode():5s}: {per * n_conns} reqs, "
              f"pipeline={pipeline}, conns={n_conns}: "
              f"{ops:,.0f} ops/sec", flush=True)
        for c in conns:
            c.writer.close()
    return results


async def amain(addrs: list[str], n_ops: int, seed: int) -> None:
    rng = random.Random(seed)
    conns = [await Conn().connect(a) for a in addrs]
    print(f"connected to {len(conns)} nodes")

    # topology: r1 meets r2; r3.. meet r2 (transitive join closes the mesh)
    await conns[0].cmd("meet", addrs[1])
    for c in conns[2:]:
        await c.cmd("meet", addrs[1])
    await poll_equal(conns, lambda c: c.cmd("get", "__mesh_probe"))
    print("mesh formed")

    await test_counters(conns, rng, n_ops)
    await test_bytes(conns, rng, n_ops)
    await test_set(conns, rng, n_ops)
    await test_dict(conns, rng, n_ops)
    print("ALL TESTS PASSED")


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="constdb-tpu-test")
    ap.add_argument("--replicas", nargs="+", required=True,
                    help="host:port of ≥2 running nodes")
    ap.add_argument("--ops", type=int, default=300)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--bench", action="store_true",
                    help="pipelined GET/SET/INCR throughput against the "
                         "first replica instead of the convergence suite")
    ap.add_argument("--bench-requests", type=int, default=100_000)
    ap.add_argument("--bench-pipeline", type=int, default=64)
    ap.add_argument("--bench-conns", type=int, default=4)
    ns = ap.parse_args(argv)
    if ns.bench:
        asyncio.run(bench_ops(ns.replicas[0], ns.bench_requests,
                              ns.bench_pipeline, ns.bench_conns))
        return
    if len(ns.replicas) < 2:
        print("need at least 2 replicas", file=sys.stderr)
        sys.exit(2)
    asyncio.run(amain(ns.replicas, ns.ops, ns.seed))


if __name__ == "__main__":
    main(sys.argv[1:])
