"""constdb-tpu-cli: interactive RESP client.

Capability parity with the reference CLI (reference bin/cli.rs:12-104):
line → words → command, pretty-printed reply, readline history, `exit`.

Usage: python -m constdb_tpu.bin.cli [-H host] [-p port]
"""

from __future__ import annotations

import argparse
import asyncio
import shlex
import sys

from ..resp.codec import make_parser, encode_msg
from ..resp.message import Arr, Bulk, Err, Int, Msg, Nil, Simple

try:
    import readline  # noqa: F401  (history + line editing)
except ImportError:
    readline = None

_HISTORY_CAP = 1024  # reference bin/cli.rs:20-24 caps at 1024 entries


def _trim_history() -> None:
    """Bound the IN-MEMORY readline history (set_history_length only caps
    write_history_file, which this CLI never calls)."""
    if readline is None:
        return
    while readline.get_current_history_length() > _HISTORY_CAP:
        readline.remove_history_item(0)


def render(m: Msg, indent: int = 0) -> str:
    pad = "  " * indent
    if isinstance(m, Nil):
        return pad + "(nil)"
    if isinstance(m, Simple):
        return pad + m.val.decode("utf-8", "replace")
    if isinstance(m, Err):
        return pad + "(error) " + m.val.decode("utf-8", "replace")
    if isinstance(m, Int):
        return pad + f"(integer) {m.val}"
    if isinstance(m, Bulk):
        return pad + f'"{m.val.decode("utf-8", "replace")}"'
    if isinstance(m, Arr):
        if not m.items:
            return pad + "(empty array)"
        return "\n".join(f"{pad}{i + 1}) {render(x, 0)}"
                         if not isinstance(x, Arr)
                         else f"{pad}{i + 1})\n{render(x, indent + 1)}"
                         for i, x in enumerate(m.items))
    return pad + repr(m)


async def repl(host: str, port: int) -> None:
    reader, writer = await asyncio.open_connection(host, port)
    parser = make_parser()
    prompt = f"{host}:{port}> "
    loop = asyncio.get_running_loop()
    while True:
        try:
            line = await loop.run_in_executor(None, input, prompt)
        except (EOFError, KeyboardInterrupt):
            break
        line = line.strip()
        _trim_history()
        if not line:
            continue
        if line.lower() in ("exit", "quit"):
            break
        try:
            words = shlex.split(line)
        except ValueError as e:
            print(f"(parse error) {e}")
            continue
        writer.write(encode_msg(Arr([Bulk(w.encode()) for w in words])))
        await writer.drain()
        while (msg := parser.next_msg()) is None:
            data = await reader.read(1 << 16)
            if not data:
                print("(connection closed)")
                return
            parser.feed(data)
        print(render(msg))
    writer.close()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="constdb-tpu-cli")
    ap.add_argument("-H", "--host", default="127.0.0.1")
    ap.add_argument("-p", "--port", type=int, default=9001)
    ns = ap.parse_args(argv)
    try:
        asyncio.run(repl(ns.host, ns.port))
    except (KeyboardInterrupt, ConnectionError) as e:
        if isinstance(e, ConnectionError):
            print(f"could not connect to {ns.host}:{ns.port}: {e}",
                  file=sys.stderr)
            sys.exit(1)


if __name__ == "__main__":
    main(sys.argv[1:])
