"""Error types for constdb-tpu.

Capability parity with the reference's error enum (reference src/lib.rs:145-181
`CstError`), re-expressed as a Python exception hierarchy.  Errors that map to
client-visible RESP errors implement `resp_error()`.
"""

from __future__ import annotations


class CstError(Exception):
    """Base error. `resp_error()` returns the RESP error text for clients."""

    def resp_error(self) -> bytes:
        return str(self).encode()


class WrongArity(CstError):
    def __init__(self, cmd: str = ""):
        super().__init__(f"wrong number of arguments for '{cmd}'" if cmd else "wrong number of arguments")


class InvalidType(CstError):
    def __init__(self) -> None:
        super().__init__("WRONGTYPE Operation against a key holding the wrong kind of value")


class UnknownCmd(CstError):
    def __init__(self, name: str):
        self.name = name
        super().__init__(f"unknown command '{name}'")


class UnknownSubCmd(CstError):
    def __init__(self, sub: str, cmd: str):
        super().__init__(f"unknown subcommand '{sub}' for '{cmd}'")


class InvalidRequestMsg(CstError):
    def __init__(self, why: str):
        super().__init__(f"invalid request: {why}")


class InvalidSnapshot(CstError):
    def __init__(self, offset: int):
        self.offset = offset
        super().__init__(f"invalid snapshot at offset {offset}")


class InvalidSnapshotChecksum(CstError):
    def __init__(self) -> None:
        super().__init__("snapshot checksum mismatch")


class ConnBroken(CstError):
    def __init__(self, addr: str = ""):
        super().__init__(f"connection broken: {addr}")


class ReplicateCommandsLost(CstError):
    """The peer's resume uuid fell out of its repl-log: must full-resync."""

    def __init__(self, addr: str = ""):
        super().__init__(f"replicate commands lost from {addr}")


class ReplicaNodeAlreadyExist(CstError):
    def __init__(self, addr: str = ""):
        super().__init__(f"replica already exists: {addr}")


class SystemError_(CstError):
    def __init__(self, why: str = "system error"):
        super().__init__(why)
