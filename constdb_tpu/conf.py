"""Configuration: TOML file + command-line overrides.

Capability parity with the reference's config system (reference
src/conf.rs:10-88 `OriginConfig`→`Config` with defaults, src/server.yml clap
args): a TOML file selected by `--config` plus flag overrides, frozen into a
`Config` dataclass at boot.  Fields keep the reference's names where the
concept carries over; TPU-specific fields are new.

Unlike the reference, `replica_heartbeat_frequency` is actually WIRED to the
pusher heartbeat (the reference parses-but-ignores it — conf.rs:81-82,
SURVEY.md §"Known reference defects").
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from dataclasses import dataclass

try:
    import tomllib  # Python >= 3.11
except ImportError:  # pragma: no cover - exercised on 3.10 images
    tomllib = None


def _mini_toml_load(f) -> dict:
    """Fallback for images without tomllib (Python 3.10): parse the flat
    scalar subset Config actually uses — `key = value` lines with quoted
    strings, ints, floats, booleans, and # comments.  Tables/arrays are
    out of scope for node configs and raise."""
    data: dict = {}
    for lineno, raw in enumerate(f.read().decode().splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("["):
            raise ValueError(
                f"config line {lineno}: TOML tables need Python >= 3.11 "
                f"(tomllib); node configs are flat key = value")
        key, sep, val = line.partition("=")
        if not sep:
            raise ValueError(f"config line {lineno}: expected key = value")
        key = key.strip()
        val = val.strip()
        if val[:1] in ('"', "'"):
            # quoted string: close at the matching quote; anything after
            # may only be whitespace or a comment (matches tomllib)
            q = val[0]
            end = val.find(q, 1)
            rest = val[end + 1:].strip() if end > 0 else "#!bad"
            if end <= 0 or (rest and not rest.startswith("#")):
                raise ValueError(f"config line {lineno}: malformed string")
            data[key] = val[1:end]
            continue
        if "#" in val:
            val = val.split("#", 1)[0].strip()
        if val in ("true", "false"):
            data[key] = val == "true"
        else:
            try:
                data[key] = int(val)
            except ValueError:
                data[key] = float(val)
    return data


# ------------------------------------------------------------ env registry
# The ONE place a CONSTDB_* tuning knob is declared.  Reads anywhere in
# the package go through the env_* helpers below (which raise on
# unregistered names), the ENV-REGISTRY lint rule rejects direct
# os.environ reads, and tests/test_analysis.py pins every registered
# name into the README "Tuning" table — so a knob cannot ship
# undeclared or undocumented.  Tools OUTSIDE the package (bench.py,
# opbench.py, tests) may still read their own CONSTDB_BENCH_*/test-only
# vars directly; the registry covers the operational surface.

@dataclass(frozen=True)
class EnvVar:
    name: str
    default: str   # rendered default, for docs/errors (not parsed)
    doc: str       # one-line effect, mirrored by the README table


ENV_REGISTRY: dict[str, EnvVar] = {v.name: v for v in (
    EnvVar("CONSTDB_SHARDS", "auto",
           "hash-shard count for the process-parallel merge; 1 = the "
           "exact single-keyspace path"),
    EnvVar("CONSTDB_SHARD_ENGINE", "tpu|cpu by node engine",
           "engine each shard worker builds (cpu keeps workers JAX-free)"),
    EnvVar("CONSTDB_SHARD_FOLD", "auto",
           "dense-fold strategy carried across the worker process "
           "boundary (workers cannot take a closure)"),
    EnvVar("CONSTDB_PIPELINE", "1",
           "stage/dispatch overlap inside merge_many; 0 = serial path"),
    EnvVar("CONSTDB_STAGE_WORKERS", "min(4, cores-1)",
           "threads in the engine's staging pool"),
    EnvVar("CONSTDB_PROBE_FAIL_TTL", "300",
           "seconds a FAILED backend probe is cached before re-probing"),
    EnvVar("CONSTDB_POOL_FLUSH_MB", "1536",
           "win-value pool cap (MB) before a streamed catch-up "
           "auto-flushes"),
    EnvVar("CONSTDB_NO_NATIVE", "",
           "any value forces the pure-Python table/RESP tiers (floor "
           "measurement)"),
    EnvVar("CONSTDB_APPLY_BATCH", "512",
           "max replicate frames coalesced into one merge on the "
           "steady-state pull path; 1 = the exact per-frame path"),
    EnvVar("CONSTDB_APPLY_LATENCY_MS", "5",
           "max ms a coalesced replicate frame may wait before its "
           "batch is force-flushed (idle streams flush immediately)"),
    EnvVar("CONSTDB_WIRE_BATCH", "512",
           "max repl-log ops group-encoded into one REPLBATCH wire "
           "frame on the push path; 1 = the byte-exact per-frame "
           "stream (and the capability is not advertised)"),
    EnvVar("CONSTDB_WIRE_LATENCY_MS", "5",
           "max ms a drained op may sit in the push loop's aggregated "
           "wire buffer before a socket flush (idle cycles flush "
           "immediately, so a lone write is never delayed)"),
    EnvVar("CONSTDB_WIRE_COMPRESS", "1",
           "negotiated replication compression (CAP_COMPRESS): REPLBATCH "
           "payloads above the floor, FULLSYNC/DELTASYNC windows, and "
           "the compressed snapshot container all gate on it; 0 = every "
           "peer gets the byte-exact plain stream and dumps stay plain"),
    EnvVar("CONSTDB_WIRE_COMPRESS_MIN", "512",
           "min REPLBATCH payload bytes before the negotiated stream "
           "compression engages (smaller payloads ship plain — framing "
           "overhead would beat the savings)"),
    EnvVar("CONSTDB_ENCODE_CACHE_MB", "16",
           "encode-once run cache cap (MB): finished wire encodings "
           "published by the first push loop to drain a run and reused "
           "by every other peer at the same cursor and caps-class; "
           "0 disables (every peer re-encodes, the pre-broadcast path)"),
    EnvVar("CONSTDB_READ_CACHE_MB", "16",
           "versioned hot-key reply cache cap (MB): finished RESP reply "
           "bytes served by the coalescer's read planner while a key's "
           "envelope version is unchanged, invalidated at every "
           "mutation intake; 0 disables (every read recomputes)"),
    EnvVar("CONSTDB_SERVE_BATCH", "512",
           "max pipelined client commands the serve path plans into one "
           "columnar merge; 1 = the exact per-command path"),
    EnvVar("CONSTDB_NATIVE_INTAKE", "1",
           "native intake stage: one C call splits a coalescing "
           "connection's pipelined chunk and classifies the plannable "
           "commands into opcodes + pre-flattened payloads; 0 = the "
           "pure drain()+run_chunk path (byte-identical output)"),
    EnvVar("CONSTDB_SERVE_LAT_SAMPLE", "32",
           "sample every Nth coalesced client command into the INFO "
           "reply-latency ring (serve_lat_p50/p99_ms); 0 = off"),
    EnvVar("CONSTDB_SERVE_SHARDS", "1",
           "serve worker processes, each owning a keyspace shard + "
           "engine + repl-log segment; 1 = the exact single-loop path"),
    EnvVar("CONSTDB_DELTA_SYNC", "1",
           "digest-driven partial resync on the replication push path; "
           "0 = always ship full snapshots"),
    EnvVar("CONSTDB_DELTA_MAX_DIVERGENCE", "0.5",
           "digest bucket-mismatch fraction past which a delta resync "
           "demotes to a full snapshot"),
    EnvVar("CONSTDB_DELTA_BUCKET_KEYS", "8",
           "target keys per digest leaf bucket (finer buckets localize "
           "divergence; 8 bytes of digest per bucket)"),
    EnvVar("CONSTDB_DELTA_STAMP_MIN", "4096",
           "min keys in the divergent buckets before the per-key stamp "
           "refinement round runs (below it, whole buckets stream)"),
    EnvVar("CONSTDB_RESIDENT", "auto",
           "steady-state device residency: a resident engine merges "
           "op-stream micro-batches in place against the resident "
           "planes; auto = only over a real (non-CPU) backend, "
           "1 = force on, 0 = always the host micro strategy"),
    EnvVar("CONSTDB_RESIDENT_WARMUP", "2",
           "consecutive micro rounds a plane's host version must stay "
           "stable before its device mirror uploads (cold planes merge "
           "on host meanwhile)"),
    EnvVar("CONSTDB_TENSOR_POOL_MB", "512",
           "resident tensor payload pool cap (MB of device bytes) "
           "before the engine flushes and releases the pools"),
    EnvVar("CONSTDB_TENSOR_MAX_ELEMS", "4194304",
           "max elements per tensor value a TENSOR.SET may create "
           "(guards one client frame from allocating GBs)"),
    EnvVar("CONSTDB_TENSOR_STRATEGY", "lww",
           "merge strategy TENSOR.SET uses when the strategy argument "
           "is '-' (lww, sum, avg, maxmag, trimmed-mean)"),
    EnvVar("CONSTDB_RECONNECT_BASE_MS", "5000",
           "replica-link reconnect backoff base delay (first retry "
           "after a drop; doubles per consecutive failure)"),
    EnvVar("CONSTDB_RECONNECT_FACTOR", "2.0",
           "replica-link reconnect backoff multiplier per consecutive "
           "dial failure"),
    EnvVar("CONSTDB_RECONNECT_MAX_MS", "60000",
           "replica-link reconnect backoff ceiling — a long partition "
           "retries at this cadence, never slower"),
    EnvVar("CONSTDB_RECONNECT_JITTER", "0.2",
           "replica-link reconnect jitter fraction, derived "
           "DETERMINISTICALLY from (node_id, peer, attempt) so chaos "
           "runs replay exactly from their seed"),
    EnvVar("CONSTDB_UNDO_WINDOW", "4096",
           "locally-originated counter ops kept undoable (CNTUNDO "
           "looks its target up here; older ops report 'evicted')"),
    EnvVar("CONSTDB_MAXMEMORY", "0",
           "governed memory ceiling in bytes (store + repl log + device "
           "pools + applier buffers); 0 = unlimited.  Past the soft "
           "watermark client DATA writes shed with an -OOM error; "
           "reads, deletes, admin, and ALL replication intake stay "
           "admitted (the convergence-soundness asymmetry, "
           "docs/INVARIANTS.md)"),
    EnvVar("CONSTDB_MAXMEMORY_SOFT_PCT", "85",
           "soft watermark as a percent of CONSTDB_MAXMEMORY: shedding "
           "starts here; at 100% of the cap the node additionally "
           "flushes device state, drops warm caches, and forces GC"),
    EnvVar("CONSTDB_CLIENT_OUTBUF_MAX", "134217728",
           "per-connection reply-buffer cap in bytes: a client that "
           "stops reading past it is disconnected loudly "
           "(client_outbuf_disconnects) instead of pinning unbounded "
           "reply memory; 0 = uncapped"),
    EnvVar("CONSTDB_REPL_WINDOW", "16777216",
           "max unacked replication-stream bytes in flight per peer: "
           "the push loop pauses draining the ring for a stalled peer "
           "at this window and resumes on REPLACK — a long stall "
           "degrades to ring eviction + delta resync; 0 = unbounded"),
    EnvVar("CONSTDB_PROTO_MAX_BULK", "536870912",
           "max declared RESP bulk-string length accepted at parse "
           "time (Redis-style 512MB default): a $-header past it is a "
           "protocol error before any buffering, in both parsers"),
    EnvVar("CONSTDB_SNAPSHOT_FSYNC", "1",
           "fsync background/shutdown snapshot dumps — file AND parent "
           "directory after the atomic rename — so a crash right after "
           "the dump cannot lose it; 0 trades that for dump latency"),
    EnvVar("CONSTDB_AOF", "0",
           "durable op log (persist/oplog.py): every repl-log append "
           "mirrors into crc-framed append-only segments in "
           "<work_dir>/aof and boot replays snapshot + oplog tail "
           "through the real merge path; 0 = in-memory only (a crash "
           "between snapshot dumps loses acknowledged writes)"),
    EnvVar("CONSTDB_AOF_FSYNC", "everysec",
           "group-commit policy: always = a serve chunk is acked only "
           "after its covering fsync lands (one fsync per pipelined "
           "chunk); everysec = background fsync every second; no = the "
           "OS decides (records still written through)"),
    EnvVar("CONSTDB_AOF_REWRITE_PCT", "100",
           "log-rewrite compaction trigger: when the oplog grows this "
           "percent past its post-rewrite base size, the node rewrites "
           "it as base snapshot + fresh segments (atomic rename + "
           "parent fsync); 0 disables auto-rewrite"),
    EnvVar("CONSTDB_AOF_REWRITE_MIN_MB", "16",
           "oplog size floor (MB) below which the rewrite trigger "
           "never fires — tiny logs are cheaper to replay than to "
           "compact"),
    EnvVar("CONSTDB_RECOVER_BULK", "1",
           "bulk-merge boot replay (persist/oplog.py): decoded AOF "
           "records accumulate into merge rounds sized like snapshot "
           "ingest chunks and land through one engine merge_many call "
           "per round; 0 pins the per-record reference path (each "
           "record merges individually — the serial replay the bench "
           "oracle compares against)"),
    EnvVar("CONSTDB_RECOVER_SHARDS", "0",
           "concurrent per-segment AOF replay on a sharded node "
           "(persist/oplog.py recover_into_plane): per-shard segments "
           "decode and route to their serve workers concurrently "
           "(cross-segment records commute — the parallel recovery "
           "law); 0 = auto (one replay task per segment), 1 = the "
           "serial merged-stream path, N caps the concurrency"),
    EnvVar("CONSTDB_CHECKPOINT_SECS", "0",
           "incremental checkpoint cadence (seconds): past it the cron "
           "cuts a consistent base snapshot + fresh AOF generation (the "
           "rewrite machinery, time-triggered), so a restart replays "
           "only the post-checkpoint tail; 0 disables (growth-triggered "
           "rewrites via CONSTDB_AOF_REWRITE_PCT still run)"),
    EnvVar("CONSTDB_CHECKPOINT_MIN_MB", "1",
           "minimum MB of post-checkpoint log tail before a time-due "
           "checkpoint actually cuts — an idle node never churns "
           "snapshots just because the clock advanced"),
    EnvVar("CONSTDB_CLUSTER", "0",
           "cluster mode (constdb_tpu/cluster): partition the 16384 "
           "hash slots (crc32(key) mod 16384 — the digest plane's own "
           "partition) across replication groups; non-owned keys get "
           "MOVED/ASK redirects and slots migrate live over the "
           "digest->delta path; 0 (default) = the exact pre-cluster "
           "single-group node, byte for byte"),
    EnvVar("CONSTDB_SLOT_GROUPS", "1",
           "bootstrap slot-table shape under CONSTDB_CLUSTER=1: the "
           "16384 slots split into this many contiguous group ranges "
           "at epoch 1 (each node's group id is supplied by the "
           "harness/operator); live migration + gossip rebalance from "
           "there"),
    EnvVar("CONSTDB_MIGRATE_BATCH_MB", "8",
           "slot-migration wire chunk (MB): a migrating slot's "
           "ColumnarBatch export streams as CLUSTER IMPORT frames of "
           "at most this size, so one big slot cannot wedge the "
           "target's loop behind a single giant frame"),
    EnvVar("CONSTDB_MIGRATE_STALL_S", "120",
           "import-window staleness timeout (seconds): a migration "
           "target whose source goes silent after SETSLOT IMPORTING — "
           "no IMPORT chunk, no STABLE, no FINALIZE — for this long "
           "drops the import window and releases its tombstone-GC pin "
           "instead of serving the slot's partial copy (and pinning "
           "GC) forever; a retried migration re-opens the window "
           "cleanly"),
    EnvVar("CONSTDB_TRACKING_BATCH", "128",
           "max invalidation keys coalesced into one RESP3 push frame "
           "per tracked connection before an immediate flush "
           "(server/tracking.py; the batch half of the dual bound)"),
    EnvVar("CONSTDB_TRACKING_LATENCY_MS", "2",
           "max milliseconds a pending invalidation key waits in a "
           "tracked connection's coalescing buffer before its push "
           "frame flushes (the latency half of the dual bound); 0 = "
           "flush on the next loop tick"),
    EnvVar("CONSTDB_TRACKING_MAX_KEYS", "65536",
           "per-connection cap on keys the default-mode tracking "
           "registry records for one client; past it the server sends "
           "a flush-all invalidation and starts over (bounded memory, "
           "never silently stale)"),
)}


def _env_read(name: str) -> str | None:
    if name not in ENV_REGISTRY:
        raise KeyError(
            f"{name} is not declared in conf.ENV_REGISTRY — register it "
            "(name, default, doc) and add a README Tuning row")
    return os.environ.get(name)


def env_str(name: str, default: str = "") -> str:
    v = _env_read(name)
    return default if v is None else v


def env_int(name: str, default: int) -> int:
    v = _env_read(name)
    return default if v is None or v == "" else int(v)


def env_float(name: str, default: float) -> float:
    v = _env_read(name)
    return default if v is None or v == "" else float(v)


def env_flag(name: str, default: bool) -> bool:
    """'0' (and only '0') is false when the variable is set — matching
    every pre-registry call site's `!= "0"` convention."""
    v = _env_read(name)
    return default if v is None or v == "" else v != "0"


@dataclass
class Config:
    # reference fields (src/conf.rs:63-88)
    daemon: bool = False          # detach (double-fork), write a pid file,
    #                               and log to a rolling file (bin/server.py;
    #                               reference src/lib.rs:89-136)
    node_id: int = 0
    node_alias: str = ""
    ip: str = "127.0.0.1"
    port: int = 9001
    threads: int = 1              # parsed for config-file compatibility with
    #                               the reference's N-IO-thread design
    #                               (src/lib.rs:138-142); this build's IO is
    #                               one asyncio loop (the loop IS the single
    #                               exec thread, so there is no parse-thread
    #                               pool to size) — values > 1 are ignored
    log: str = "console"          # "console" | path to a log file
    work_dir: str = "./"
    tcp_backlog: int = 1024       # wired to the listen backlog (server/io.py;
    #                               reference src/server.rs:96-101)
    replica_heartbeat_frequency: int = 4   # seconds (wired, unlike reference)
    replica_gossip_frequency: int = 15     # seconds between reconnect dials
    # new (TPU build)
    addr: str = ""                # advertised address, default ip:port
    engine: str = "auto"          # "auto" | "tpu" | "tpu!" | "cpu"
    #                               "tpu" falls back to XLA-on-CPU (with a
    #                               warning + INFO engine_degraded) when no
    #                               accelerator is healthy; "tpu!" fails
    #                               fast at boot instead
    snapshot_path: str = ""       # load on boot + background dump target
    snapshot_interval: int = 0    # seconds between background dumps (0 = off)
    snapshot_chunk_keys: int = 1 << 16
    snapshot_compress_level: int = 1  # zlib level for snapshot sections —
    #                               on disk AND on the wire (full sync
    #                               streams the same file; reference
    #                               src/conn/writer.rs:92-112 streams raw).
    #                               0 = store/send raw; 1 (default) = fast;
    #                               up to 9 = smallest
    repl_log_cap: int = 1_024_000  # reference src/server.rs:81
    log_level: str = "info"
    pid_file: str = ""            # default: <work_dir>/constdb.pid (daemon)
    log_max_bytes: int = 64 << 20  # rolling-log size cap per file
    log_backups: int = 4           # rolled files kept
    ingest_shards: int = 0  # process-parallel snapshot ingest: hash-shard
    #                         a large downloaded snapshot across this many
    #                         worker processes (store/sharded_keyspace.py).
    #                         0 = auto (CONSTDB_SHARDS env / core count;
    #                         stays 1 on <= 2 cores), 1 = off.
    ingest_shard_min_bytes: int = 64 << 20  # snapshots below this take the
    #                         plain single-keyspace path (worker spawn
    #                         costs more than it saves on small syncs)
    serve_shards: int = 0  # shard-per-core serving (server/serve_shards.py):
    #                        N worker processes each owning a keyspace shard
    #                        + engine + repl-log segment, the event loop
    #                        routing by key hash.  0 = the CONSTDB_SERVE_SHARDS
    #                        env default (1); 1 = the exact single-loop path.
    aof: bool = False      # durable op log (persist/oplog.py): mirror
    #                        every repl-log append into crc-framed
    #                        append-only segments under aof_dir and
    #                        replay snapshot + oplog tail on boot.
    #                        False = the CONSTDB_AOF env default decides.
    aof_fsync: str = ""    # "always" | "everysec" | "no"; "" = the
    #                        CONSTDB_AOF_FSYNC env default (everysec)
    aof_rewrite_pct: int = -1  # log-rewrite growth trigger (percent over
    #                        the post-rewrite base; 0 = off); -1 = the
    #                        CONSTDB_AOF_REWRITE_PCT env default (100)
    aof_dir: str = ""      # segment directory; "" = <work_dir>/aof
    cluster_group: int = 0  # this node's replication-group id under
    #                        CONSTDB_CLUSTER=1 (which slot range of the
    #                        CONSTDB_SLOT_GROUPS bootstrap split it
    #                        owns); every member of a group shares one
    #                        id.  Deliberately a flag, not an env: two
    #                        nodes of one cluster differ ONLY here.
    restore_to: int = 0    # point-in-time restore: boot replays the AOF
    #                        only up to this uuid (record-boundary
    #                        granularity), then re-bases the log on the
    #                        restored state.  Run it against a COPY of
    #                        the data dir — the skipped suffix is
    #                        discarded by the re-basing checkpoint.
    #                        0 = full recovery (the normal boot).
    # a peer silent for longer than this stops pinning the GC tombstone
    # horizon.  0 (default) = never exclude — the reference's behavior,
    # where one dead peer pins tombstone collection mesh-wide forever
    # (reference replica/replica.rs:87-89).  When enabled, an excluded
    # peer whose tombstones were collected AND whose resume point fell
    # off the repl_log is forced through a STATE-CLEARING full resync on
    # return (link.py fullsync reset flag): its local keyspace and
    # repl_log are wiped before the snapshot merge, so stale keys cannot
    # resurrect mesh-wide — at the cost of discarding any writes the
    # excluded peer made while partitioned.  While the repl_log still
    # covers its resume point, partial replay stays lossless.
    gc_peer_retention: int = 0  # seconds (0 = off)


def load_config(argv: list[str] | None = None) -> Config:
    """`constdb-tpu-server [config.toml] [-h HOST] [-p PORT] ...`
    (reference bin/server.rs + server.yml arg spec)."""
    ap = argparse.ArgumentParser(prog="constdb-tpu-server",
                                 description="constdb-tpu node")
    ap.add_argument("config", nargs="?", help="TOML config file")
    ap.add_argument("--host", "-H", dest="ip")
    ap.add_argument("--port", "-p", type=int)
    ap.add_argument("--node-id", type=int, dest="node_id")
    ap.add_argument("--alias", dest="node_alias")
    ap.add_argument("--addr", help="advertised address (host:port)")
    ap.add_argument("--work-dir", dest="work_dir")
    ap.add_argument("--engine", choices=["auto", "tpu", "tpu!", "cpu"])
    ap.add_argument("--snapshot", dest="snapshot_path")
    ap.add_argument("--snapshot-interval", type=int, dest="snapshot_interval")
    ap.add_argument("--aof", action="store_const", const=True, dest="aof",
                    default=None, help="enable the durable op log")
    ap.add_argument("--aof-fsync", dest="aof_fsync",
                    choices=["always", "everysec", "no"])
    ap.add_argument("--restore-to", type=int, dest="restore_to",
                    metavar="UUID",
                    help="point-in-time restore: replay the AOF only up "
                         "to this uuid, then re-base the log (run "
                         "against a copy of the data dir)")
    ap.add_argument("--cluster-group", type=int, dest="cluster_group",
                    metavar="GID",
                    help="this node's replication-group id under "
                         "CONSTDB_CLUSTER=1 (default 0; see "
                         "CONSTDB_SLOT_GROUPS)")
    ap.add_argument("--log-level", dest="log_level")
    ns = ap.parse_args(argv)

    cfg = Config()
    if ns.config:
        with open(ns.config, "rb") as f:
            data = tomllib.load(f) if tomllib is not None \
                else _mini_toml_load(f)
        for field in dataclasses.fields(Config):
            if field.name in data:
                setattr(cfg, field.name, data[field.name])
    for field in dataclasses.fields(Config):
        v = getattr(ns, field.name, None)
        if v is not None:
            setattr(cfg, field.name, v)
    return cfg


def build_engine(kind: str):
    """'auto' prefers the TPU engine when a device backend initializes.

    Backend health is checked OUT-OF-PROCESS first (utils/backend.py):
    a wedged tunnel-attached device hangs in-process init forever, which
    would wedge node boot under engine="auto".  Probe says healthy →
    init for real; probe fails → pin this process to the CPU platform
    (so nothing later in the server accidentally hangs) and fall back.

    'tpu' falls back to the XLA-on-CPU engine when no accelerator is
    healthy — the node keeps serving, orders of magnitude slower; the
    degradation is surfaced in logs AND in INFO (`engine_degraded`, via
    the engine's `degraded` attribute).  'tpu!' is the strict variant:
    no healthy accelerator is a BOOT FAILURE (a driver outage or
    misconfiguration should page, not limp)."""
    strict = kind == "tpu!"
    if kind in ("auto", "tpu", "tpu!"):
        from .utils.backend import force_cpu_platform, probe_backend

        probe = probe_backend()
        if probe.ok and probe.platform != "cpu":
            try:
                from .engine.tpu import TpuMergeEngine
                # resident: per-family device state persists across merge
                # rounds — the steady-state engine of round 12 (op-stream
                # micro-batches merge in place per CONSTDB_RESIDENT, and
                # bulk catch-up pays row uploads only, never a state
                # round-trip per chunk); Node.ensure_flushed syncs before
                # every host read
                return TpuMergeEngine(resident=True)
            except Exception:
                # device vanished between probe and real init
                if kind in ("tpu", "tpu!"):
                    raise
                force_cpu_platform()
        elif strict:
            raise RuntimeError(
                "engine='tpu!' requires a healthy accelerator backend: "
                + (probe.error or f"default backend is {probe.platform}"))
        elif kind == "tpu":
            # a node that cannot find its accelerator must still SERVE: the
            # XLA engine on the CPU backend runs the same batched kernels
            # (falling back keeps the operator's config portable; the
            # warning + INFO engine_degraded make the degradation visible)
            import logging
            reason = probe.error or f"default backend is {probe.platform}"
            logging.getLogger(__name__).warning(
                "engine='tpu' requested but no healthy device backend (%s); "
                "falling back to the XLA-on-CPU engine", reason)
            force_cpu_platform()
            try:
                from .engine.tpu import TpuMergeEngine
                eng = TpuMergeEngine(resident=True)  # see the healthy
                # branch above; steady residency still gates on
                # CONSTDB_RESIDENT=auto, which stays host-side on CPU
                eng.degraded = f"tpu requested, running XLA-on-CPU: {reason}"
                return eng
            except Exception:
                pass  # no usable XLA at all: plain CPU engine below
        if not probe.ok:
            force_cpu_platform()
    from .engine.cpu import CpuMergeEngine
    eng = CpuMergeEngine()
    if kind == "tpu":
        eng.degraded = "tpu requested, running the pure-CPU engine"
    return eng
