#!/usr/bin/env python
"""Headline benchmark: batched CRDT snapshot-merge throughput.

Scenario (BASELINE.json north-star): a node catches up by merging R replica
snapshots of an N-key mixed keyspace (PN-counters, LWW registers, ORSets)
into an empty local store, STREAMED in chunks exactly the way the replica
link applies a downloaded snapshot (persist/snapshot.py chunk sections →
one engine merge per chunk) — the bulk path the reference walks one key at
a time via `DB::merge_entry` → `Object::merge` (reference src/db.rs:31-43,
src/object.rs:63-83).  The TPU engine runs device-RESIDENT: chunk merges
keep state in HBM and the timed span includes the final flush back to the
host keyspace, so both engines end fully host-queryable.

Prints ONE JSON line:
  {"metric": "snapshot_merge_keys_per_sec", "value": <TPU-engine keys/sec>,
   "unit": "keys/sec", "vs_baseline": <speedup over the CPU MergeEngine>}

Sizing knobs (env): CONSTDB_BENCH_KEYS (default 1_000_000),
CONSTDB_BENCH_REPLICAS (default 8), CONSTDB_BENCH_CPU_KEYS (defaults to
CONSTDB_BENCH_KEYS so the baseline rate is same-scale; set lower to cap the
pure-Python run), CONSTDB_BENCH_CHUNK (keys per chunk, default 131072).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from constdb_tpu.crdt import semantics as S
from constdb_tpu.engine.base import ColumnarBatch
from constdb_tpu.engine.cpu import CpuMergeEngine
from constdb_tpu.store.keyspace import KeySpace
from constdb_tpu.utils.hlc import SEQ_BITS

_I64 = np.int64
MS0 = 1_700_000_000_000  # fixed epoch so uuids look like real HLC values


def ensure_native(timeout: float = 600.0) -> None:
    """Build the native extension (native/ C++ tables + RESP codec) when
    its artifacts are missing.  The toolchain is baked into the image and
    the build is one `make` call; without it every interning/index batch
    call falls back to pure-Python tiers — the single largest host
    dispatch cost measured in the BENCH_r05 profile.  CONSTDB_AUTO_NATIVE=0
    skips; failures degrade to the pure tiers, never abort the bench."""
    if os.environ.get("CONSTDB_AUTO_NATIVE", "1") == "0":
        return
    if os.environ.get("CONSTDB_NO_NATIVE"):
        return  # pure-tier floor measurement: building would be wasted
    from constdb_tpu.utils import native_tables as NT

    if NT.load_ext() is not None:
        return
    mkdir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
    if not os.path.exists(os.path.join(mkdir, "Makefile")):
        return
    import subprocess

    t0 = time.perf_counter()
    try:
        r = subprocess.run(["make", "-C", mkdir], capture_output=True,
                           timeout=timeout, text=True)
    except Exception as e:
        print(f"[bench] native build skipped: {e}", file=sys.stderr)
        return
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        print(f"[bench] native build failed rc={r.returncode}: "
              f"{tail[-1] if tail else ''}", file=sys.stderr)
        return
    ok = NT.reload_tiers()
    print(f"[bench] native extension built in "
          f"{time.perf_counter() - t0:.1f}s (loaded={ok})", file=sys.stderr)


def host_fingerprint() -> dict:
    """Box identity stamped into every bench JSON line: cross-box
    comparisons (the r05/r06 host_note confusion) become a field check
    instead of prose archaeology."""
    import platform

    model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith(("model name", "hardware")):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    return {
        "cpu_model": model or platform.processor() or platform.machine(),
        "cores": os.cpu_count(),
        "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
        "platform": platform.platform(),
    }


def engine_counters(engine) -> dict:
    """Device-transfer gauges stamped into every JSON line that has an
    engine in reach (the residency metrics BENCH_r12 and the v5e
    follow-up round read; zeros for host-only engines)."""
    return {
        "dev_upload_bytes": getattr(engine, "bytes_h2d", 0),
        "dev_download_bytes": getattr(engine, "bytes_d2h", 0),
        "dev_rounds_resident": getattr(engine, "dev_rounds_resident", 0),
        "host_micro_rounds": getattr(engine, "host_micro_rounds", 0),
        "flush_rows_downloaded": getattr(engine, "flush_rows_downloaded", 0),
        "flush_rows_full_equiv": getattr(engine, "flush_rows_full_equiv", 0),
        "pallas_broken": bool(getattr(engine, "_pallas_broken", False)),
    }


def _uuids(rng, n, span_ms=600_000):
    # float-scaled draws: ~5x faster than bounded-integer rejection
    # sampling at the 10M scale (this is workload GENERATION — outside the
    # timed span, but inside the driver's wall clock)
    ms = (rng.random(n) * span_ms).astype(_I64)
    seq = (rng.random(n) * (1 << 10)).astype(_I64)
    return ((MS0 + ms) << SEQ_BITS) | seq


def make_workload(n_keys: int, n_replicas: int, seed: int = 7,
                  members_per_set: int = 4, hlc_order: bool = False):
    """R snapshot batches over one mixed N-key keyspace.

    40% counters / 30% registers / 30% sets.  Immutable columns (key bytes,
    enc, member bytes) are built once and shared across batches — replica
    snapshots of the same keyspace really do share this data.

    `hlc_order`: sort every uuid draw so columns are near-monotone in
    key order — the shape a REAL node's dump has (keys created over
    time, dumped in creation order; HLC uuids are arrival-ordered).
    The default uniform-random draw is the adversarial shape for the
    compressed-container bytes leg (uuid columns become incompressible
    noise no real store produces).
    """
    rng = np.random.default_rng(seed)

    def draw(n):
        u = _uuids(rng, n)
        if hlc_order:
            u.sort()
        return u
    keys = [b"k%010d" % i for i in range(n_keys)]
    enc = np.empty(n_keys, dtype=np.int8)
    n_cnt = int(n_keys * 0.4)
    n_reg = int(n_keys * 0.3)
    n_set = n_keys - n_cnt - n_reg
    enc[:n_cnt] = S.ENC_COUNTER
    enc[n_cnt:n_cnt + n_reg] = S.ENC_BYTES
    enc[n_cnt + n_reg:] = S.ENC_SET

    reg_pool = [b"v%06d" % i for i in range(1024)]
    reg_idx = rng.integers(0, len(reg_pool), n_reg)
    member_pool = [b"m%04d" % i for i in range(4096)]

    set_ki = np.repeat(np.arange(n_cnt + n_reg, n_keys, dtype=_I64),
                       members_per_set)
    member_idx = rng.integers(0, len(member_pool), len(set_ki))
    # batches declare rows_unique_per_slot: drop duplicate (key, member)
    # draws so the claim actually holds (a collision would make the
    # unique-indices scatter order-dependent)
    combo = (set_ki << 32) | member_idx
    _, first = np.unique(combo, return_index=True)
    first.sort()
    set_ki = set_ki[first]
    member_idx = member_idx[first]
    el_member = [member_pool[i] for i in member_idx]
    el_val = [None] * len(set_ki)

    batches = []
    for r in range(n_replicas):
        b = ColumnarBatch()
        b.rows_unique_per_slot = True
        b.keys = keys
        b.key_enc = enc
        b.key_ct = draw(n_keys)
        b.key_mt = b.key_ct + (rng.integers(0, 1000, n_keys) << SEQ_BITS)
        # ~2% of keys tombstoned later than their create time
        dt = np.where(rng.random(n_keys) < 0.02,
                      b.key_mt + (1 << SEQ_BITS), 0)
        b.key_dt = dt.astype(_I64)
        b.key_expire = np.zeros(n_keys, dtype=_I64)

        b.reg_val = [None] * n_cnt + [reg_pool[i] for i in reg_idx] + \
                    [None] * n_set
        b.reg_t = np.zeros(n_keys, dtype=_I64)
        b.reg_t[n_cnt:n_cnt + n_reg] = draw(n_reg)
        b.reg_node = np.zeros(n_keys, dtype=_I64)
        b.reg_node[n_cnt:n_cnt + n_reg] = r + 1

        # each replica snapshot carries that replica's own counter slot
        b.cnt_ki = np.arange(n_cnt, dtype=_I64)
        b.cnt_node = np.full(n_cnt, r + 1, dtype=_I64)
        b.cnt_val = rng.integers(-1000, 1000, n_cnt).astype(_I64)
        b.cnt_uuid = draw(n_cnt)
        b.cnt_base = np.zeros(n_cnt, dtype=_I64)
        b.cnt_base_t = np.full(n_cnt, S.NEUTRAL_T, dtype=_I64)

        b.el_ki = set_ki
        b.el_member = el_member
        b.el_val = el_val
        b.el_add_t = draw(len(set_ki))
        b.el_add_node = np.full(len(set_ki), r + 1, dtype=_I64)
        b.el_del_t = np.where(rng.random(len(set_ki)) < 0.1,
                              draw(len(set_ki)), 0).astype(_I64)
        batches.append(b)
    return batches


def subsample_keys(keys, n_keys: int, target: int = 100_000) -> list:
    """Key bytes of the verification subsample — the ONE home for the
    every-`step`-th-key formula (subsample_workload derives from it, and
    the bench parent uses it while the oracle replay runs in a worker)."""
    step = max(1, n_keys // target)
    return [keys[i] for i in range(0, n_keys, step)]


def subsample_workload(batches, n_keys: int, target: int = 100_000):
    """Deterministic per-key filter of a workload: every `step`-th key,
    with counter/element rows remapped.  Per-key merges are independent,
    so a CPU replay of the FILTERED batches is an exact oracle for those
    keys in the full device-merged store (bench verification)."""
    step = max(1, n_keys // target)
    keep = np.arange(0, n_keys, step)
    sub_keys = subsample_keys(batches[0].keys, n_keys, target)
    out = []
    for b in batches:
        fb = ColumnarBatch()
        fb.rows_unique_per_slot = b.rows_unique_per_slot
        fb.keys = sub_keys
        fb.key_enc = b.key_enc[keep]
        fb.key_ct = b.key_ct[keep]
        fb.key_mt = b.key_mt[keep]
        fb.key_dt = b.key_dt[keep]
        fb.key_expire = b.key_expire[keep]
        fb.reg_val = [b.reg_val[i] for i in keep.tolist()]
        fb.reg_t = b.reg_t[keep]
        fb.reg_node = b.reg_node[keep]
        cm = (b.cnt_ki % step) == 0
        fb.cnt_ki = b.cnt_ki[cm] // step
        for col in ("cnt_node", "cnt_val", "cnt_uuid", "cnt_base",
                    "cnt_base_t"):
            setattr(fb, col, getattr(b, col)[cm])
        em = (b.el_ki % step) == 0
        rows = np.nonzero(em)[0].tolist()
        fb.el_ki = b.el_ki[em] // step
        fb.el_member = [b.el_member[i] for i in rows]
        fb.el_val = [b.el_val[i] for i in rows]
        for col in ("el_add_t", "el_add_node", "el_del_t"):
            setattr(fb, col, getattr(b, col)[em])
        out.append(fb)
    return out, sub_keys


def oracle_canonical(batches, n_keys: int, target: int = 100_000) -> dict:
    """CPU-replay a deterministic ~`target`-key subsample of the workload
    and return its canonical state (the verification oracle)."""
    sub, _sub_keys = subsample_workload(batches, n_keys, target)
    oracle = KeySpace()
    cpu = CpuMergeEngine()
    for b in sub:
        cpu.merge(oracle, b)
    return oracle.canonical()


def compare_canonical(got: dict, want: dict) -> int:
    """Diff count between device and oracle canonical states (prints the
    first few mismatches)."""
    if got == want:
        return 0
    diff = [k for k in want if got.get(k) != want[k]]
    diff += [k for k in got if k not in want]
    for k in diff[:5]:
        print(f"[bench] VERIFY MISMATCH {k!r}:\n  device={got.get(k)!r}"
              f"\n  oracle={want.get(k)!r}", file=sys.stderr)
    return len(diff)


def verify_store(store, batches, n_keys: int, target: int = 100_000):
    """Oracle check of the device-merged store: CPU-replay a deterministic
    ~`target`-key subsample of the same workload and canonical()-compare.
    Returns (ok, n_checked, n_diff)."""
    sub_keys = subsample_keys(batches[0].keys, n_keys, target)
    want = oracle_canonical(batches, n_keys, target)
    n_diff = compare_canonical(store.canonical(keys=sub_keys), want)
    return n_diff == 0, len(sub_keys), n_diff


def _oracle_worker(conn, batches, n_keys: int, target: int) -> None:
    """Forked verify worker: sleeps on the pipe until the parent's "go"
    (sent after the timed merges, so the replay never competes with the
    measured run), then replays the subsample on the CPU engine and ships
    the oracle canonical state back."""
    try:
        conn.recv()  # block until the timed runs complete
        conn.send(oracle_canonical(batches, n_keys, target))
    except BaseException as e:  # surfaced (and re-raised) by the parent
        conn.send(e)
    finally:
        conn.close()


def start_oracle(batches, n_keys: int, target: int = 100_000):
    """Fork the oracle replay worker (copy-on-write: the workload is NOT
    re-pickled).  MUST be called before any in-process jax init — forking
    a JAX-threaded process can deadlock the child — which is why main()
    generates the workload and forks ahead of the backend import; the
    worker idles until go() anyway.  -> (process, conn), or None if fork
    is unavailable (the caller then falls back to the serial verify)."""
    import multiprocessing as mp

    try:
        ctx = mp.get_context("fork")
    except ValueError:  # pragma: no cover - non-POSIX
        return None
    parent, child = ctx.Pipe()
    p = ctx.Process(target=_oracle_worker,
                    args=(child, batches, n_keys, target), daemon=True)
    p.start()
    child.close()
    return p, parent


def probe_link(jax, mb: int = 64, repeats: int = 3):
    """Measured host<->device bandwidth (bytes/s up, down): device_put /
    device_get of a `mb`-MB buffer, best of `repeats`.  On a
    tunnel-attached chip this is the wall-clock ceiling for the
    transfer-bound merge; on local PCIe/CPU backends it is ~memcpy."""
    dev = jax.devices()[0]
    buf = np.random.default_rng(0).integers(  # incompressible
        0, 1 << 62, (mb << 20) // 8, dtype=np.int64)
    jax.device_put(np.zeros(1024, dtype=np.int64), dev).block_until_ready()
    up = down = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        x = jax.device_put(buf, dev)
        x.block_until_ready()
        up = max(up, buf.nbytes / (time.perf_counter() - t0))
        t0 = time.perf_counter()
        back = np.asarray(x)
        down = max(down, back.nbytes / (time.perf_counter() - t0))
        del x, back
    return up, down


def chunk_batches(batches, chunk_keys: int):
    """Interleave replicas' snapshot chunks (the arrival order during a
    real multi-peer catch-up)."""
    from constdb_tpu.persist.snapshot import batch_chunks

    per_replica = [list(batch_chunks(b, chunk_keys)) for b in batches]
    out = []
    for i in range(max(len(p) for p in per_replica)):
        for p in per_replica:
            if i < len(p):
                out.append(p[i])
    return out


def time_engine(make_engine, chunks, repeats: int = 2,
                group: int = 1):
    """Best wall-time over `repeats` streamed catch-ups into a fresh store
    (includes the final flush for resident engines).  `group` > 1 feeds
    that many consecutive chunks per engine call (merge_many) — with the
    interleaved arrival order, groups of n_replicas are slot-ALIGNED and
    take the engine's fused dense-fold path (one scatter per group).
    Returns (best_seconds, last_run_store) — the store feeds the oracle
    verification."""
    best = float("inf")
    store = None
    for _ in range(repeats):
        engine = make_engine()
        store = KeySpace()
        t0 = time.perf_counter()
        if group > 1 and hasattr(engine, "merge_many"):
            for i in range(0, len(chunks), group):
                engine.merge_many(store, chunks[i:i + group])
        else:
            for c in chunks:
                engine.merge(store, c)
        if getattr(engine, "needs_flush", False):
            engine.flush(store)
        best = min(best, time.perf_counter() - t0)
    return best, store


# --------------------------------------------------------------------------
# --mode stream: steady-state replication apply (the coalescing pull path)


def make_frame_log(n_frames: int, n_keys: int, seed: int = 11) -> list:
    """Deterministic replicate-frame log over a mixed keyspace — the
    shape one peer's steady-state stream has on the wire (REPLICATE
    frames with monotone HLC uuids from one origin), including the DEL
    rewrites that act as coalescer barriers."""
    import random

    from constdb_tpu.resp.message import Bulk, Int

    rng = random.Random(seed)
    frames = []
    prev = 0
    for i in range(1, n_frames + 1):
        uuid = (MS0 + i) << SEQ_BITS
        k = b"%06d" % rng.randrange(n_keys)
        r = rng.random()
        if r < 0.30:
            body = (b"set", b"r" + k, b"v%08d" % i)
        elif r < 0.52:
            body = (b"cntset", b"c" + k, rng.randrange(-10_000, 10_000))
        elif r < 0.72:
            # multi-member set writes (tag/follower-list shape)
            body = (b"sadd", b"s" + k,
                    *(b"m%03d" % rng.randrange(64) for _ in range(4)))
        elif r < 0.80:
            body = (b"srem", b"s" + k, b"m%03d" % rng.randrange(64))
        elif r < 0.90:
            # multi-field record writes (YCSB's canonical user-record
            # workload writes 10 fields per op; 5 here is conservative)
            fv = []
            for f in range(5):
                fv += [b"f%02d" % rng.randrange(16), b"v%07d%d" % (i, f)]
            body = (b"hset", b"h" + k, *fv)
        elif r < 0.995:
            body = (b"hdel", b"h" + k, b"f%02d" % rng.randrange(16))
        elif r < 0.998:
            body = (b"delbytes", b"r" + k)   # scalar DEL: coalesces
        else:
            body = (b"delset", b"s" + k)     # collection DEL: barrier
        # DELs are ~0.5% of the stream: ConstDB's serving workload is
        # write-once constant data (PAPER.md), so deletes are
        # administrative, not steady-state — but they must be PRESENT so
        # the bench exercises the barrier flush machinery for real
        frames.append([Bulk(b"replicate"), Int(99), Int(prev), Int(uuid),
                       Bulk(body[0]),
                       *[Int(a) if isinstance(a, int) else Bulk(a)
                         for a in body[1:]]])
        prev = uuid
    return frames


def save_frame_log(path: str, frames: list) -> None:
    from constdb_tpu.resp.codec import encode_msg
    from constdb_tpu.resp.message import Arr

    with open(path, "wb") as f:
        for items in frames:
            f.write(encode_msg(Arr(items)))


def load_frame_log(path: str) -> list:
    from constdb_tpu.resp.codec import make_parser

    parser = make_parser()
    frames = []
    with open(path, "rb") as f:
        while True:
            data = f.read(1 << 20)
            if not data:
                break
            parser.feed(data)
            while (msg := parser.next_msg()) is not None:
                frames.append(msg.items)
    return frames


def replay_stream(frames, make_engine, apply_batch: int,
                  latency_s: float):
    """Replay a frame log through the coalescing applier exactly the way
    the pull loop drives it.  Returns (node, wall_seconds,
    per-frame visibility latencies) — visibility = intake→landed."""
    from constdb_tpu.replica.coalesce import CoalescingApplier
    from constdb_tpu.replica.manager import ReplicaMeta
    from constdb_tpu.server.node import Node

    node = Node(node_id=1, engine=make_engine())
    applier = CoalescingApplier(node, ReplicaMeta("bench-peer:0"),
                                max_frames=apply_batch,
                                max_latency=latency_s,
                                now=time.perf_counter)
    # visibility latency is SAMPLED (every 64th frame): per-frame clock
    # reads would tax the measured path itself, and ~1.5% of a frame log
    # is ample for a p99.  Sampled frames drain into `lat` when the
    # batch covering them actually LANDS (merge_stream_batch hook) — the
    # definition of visibility the coalescer's watermark rule uses.
    lat: list[float] = []
    pending_ts: list[float] = []
    clock = time.perf_counter
    real_land = node.merge_stream_batch

    def landing(bb, n):
        real_land(bb, n)
        now = clock()
        lat.extend(now - t for t in pending_ts)
        pending_ts.clear()

    node.merge_stream_batch = landing
    t0 = clock()
    for i, items in enumerate(frames):
        applier.apply(items)
        if not i & 63:
            if not applier.pending:  # landed immediately (barrier /
                lat.append(0.0)      # per-frame path)
            else:
                pending_ts.append(clock())
    applier.flush()
    node.ensure_flushed()
    end = clock()
    lat.extend(end - t for t in pending_ts)
    node.merge_stream_batch = real_land
    return node, end - t0, lat


def stream_resident_legs(args, frames, n_keys, apply_batch, latency_s,
                         backend, note) -> None:
    """`--resident 0,1` stream legs: interleaved best-of-3 replays of the
    SAME frame log through a device-resident engine (steady in-place
    micro merges) vs the host-path engine (resident=0 routes micro
    batches to engine/hostbatch), each oracle-verified against the
    per-frame CPU replay, with per-leg transfer counters (BENCH_r12)."""
    from constdb_tpu.engine.tpu import TpuMergeEngine

    legs = [int(x) for x in str(args.resident).split(",")]
    # CONSTDB_BENCH_FOLD carries the kernel-backend forcing into the leg
    # engines (ci.sh runs the resident smoke under pallas-interpret)
    fold = os.environ.get("CONSTDB_BENCH_FOLD", "auto")
    best = {r: (float("inf"), None) for r in legs}
    base_wall, base_node = float("inf"), None
    for _ in range(3):
        for r in legs:
            n_, w_, _ = replay_stream(
                frames,
                # steady FORCED per leg: the auto default only engages
                # over a real accelerator, and this leg measures the
                # path itself (the host note flags the CPU-box caveat)
                lambda: TpuMergeEngine(resident=bool(r), steady=bool(r),
                                       dense_fold=fold),
                apply_batch=apply_batch, latency_s=latency_s)
            if w_ < best[r][0]:
                best[r] = (w_, n_)
        bn_, bw_, _ = replay_stream(frames, CpuMergeEngine,
                                    apply_batch=1, latency_s=1.0)
        if bw_ < base_wall:
            base_node, base_wall = bn_, bw_
    want = base_node.canonical()
    curve = []
    verified = True
    for r in legs:
        w_, n_ = best[r]
        diffs = compare_canonical(n_.canonical(), want)
        verified = verified and diffs == 0
        leg = {"resident": r, "wall_s": round(w_, 3),
               "fps": round(len(frames) / w_, 1),
               "coalesce_flushes": n_.stats.repl_coalesce_flushes,
               "apply_barriers": n_.stats.repl_apply_barriers,
               "diffs": diffs}
        leg.update(engine_counters(n_.engine))
        curve.append(leg)
        print(f"[bench] resident={r}: {w_:.3f}s = {leg['fps']:,.0f} "
              f"frames/s; dev rounds {leg['dev_rounds_resident']}, host "
              f"rounds {leg['host_micro_rounds']}, flush rows "
              f"{leg['flush_rows_downloaded']}/"
              f"{leg['flush_rows_full_equiv']}, h2d "
              f"{leg['dev_upload_bytes']:,} d2h "
              f"{leg['dev_download_bytes']:,} "
              f"({'OK' if diffs == 0 else 'MISMATCH'})", file=sys.stderr)
        if hasattr(n_.engine, "close"):
            n_.engine.close()
    base_fps = len(frames) / base_wall
    out = {
        "metric": "stream_apply_frames_per_sec",
        "value": curve[-1]["fps"],
        "unit": "frames/sec",
        "mode": "stream",
        "frames": len(frames),
        "stream_keys": n_keys,
        "apply_batch": apply_batch,
        "per_frame_baseline_fps": round(base_fps, 1),
        "resident_curve": curve,
        "backend": backend,
        "verified": verified,
        "host": host_fingerprint(),
    }
    if note:
        out["note"] = note
    print(json.dumps(out))
    if not verified:
        sys.exit(1)


def stream_main(args) -> None:
    """`bench.py --mode stream`: coalesced steady-state apply vs the
    exact per-frame path (CONSTDB_APPLY_BATCH=1 degenerate), replaying
    one recorded frame log through both and oracle-comparing the final
    stores.  Emits ONE JSON line with frames/s + p99 visibility."""
    n_frames = int(os.environ.get("CONSTDB_BENCH_FRAMES", 200_000))
    n_keys = int(os.environ.get("CONSTDB_BENCH_STREAM_KEYS", 20_000))
    apply_batch = int(os.environ.get("CONSTDB_BENCH_APPLY_BATCH", 4096))
    latency_s = float(os.environ.get("CONSTDB_BENCH_APPLY_LATENCY_MS",
                                     1000.0)) / 1000.0
    engine_kind = os.environ.get("CONSTDB_BENCH_STREAM_ENGINE", "xla")

    ensure_native()
    if args.frame_log and os.path.exists(args.frame_log):
        frames = load_frame_log(args.frame_log)
        print(f"[bench] replaying recorded frame log {args.frame_log}: "
              f"{len(frames)} frames", file=sys.stderr)
    else:
        t0 = time.perf_counter()
        frames = make_frame_log(n_frames, n_keys)
        print(f"[bench] frame log gen: {len(frames)} frames over "
              f"~{n_keys} keys in {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        if args.frame_log:
            save_frame_log(args.frame_log, frames)
            print(f"[bench] recorded to {args.frame_log}", file=sys.stderr)

    note = ""
    if engine_kind == "cpu":
        make_engine = CpuMergeEngine
        backend = "none"
    else:
        from constdb_tpu.utils.backend import (force_cpu_platform,
                                               probe_backend)

        probe = probe_backend()
        if not probe.ok:
            note = (f"device backend unavailable ({probe.error}); "
                    "XLA-on-CPU fallback")
            print(f"[bench] WARNING: {note}", file=sys.stderr)
            force_cpu_platform()
        from constdb_tpu.engine.tpu import TpuMergeEngine
        import jax

        backend = jax.default_backend()
        make_engine = TpuMergeEngine

    if args.resident is not None:
        stream_resident_legs(args, frames, n_keys, apply_batch, latency_s,
                             backend, note)
        return

    # both paths replay the SAME log, interleaved, best-of-3 (the same
    # convention the snapshot bench uses — one unlucky run on a shared
    # box must not be the round's number).  The per-frame leg
    # (apply_batch=1 routes every frame through node.apply_replicated —
    # the pre-coalescing hot loop) doubles as the verification oracle.
    wall = base_wall = float("inf")
    node = base_node = lat = None
    for _ in range(3):
        n_, w_, l_ = replay_stream(frames, make_engine,
                                   apply_batch=apply_batch,
                                   latency_s=latency_s)
        if w_ < wall:
            node, wall, lat = n_, w_, l_
        bn_, bw_, _ = replay_stream(frames, CpuMergeEngine,
                                    apply_batch=1, latency_s=1.0)
        if bw_ < base_wall:
            base_node, base_wall = bn_, bw_
    base_fps = len(frames) / base_wall
    print(f"[bench] per-frame path: {base_wall:.3f}s = "
          f"{base_fps:,.0f} frames/s", file=sys.stderr)
    fps = len(frames) / wall
    lat_ms = np.asarray(lat) * 1000.0
    p50, p99 = (float(np.percentile(lat_ms, q)) for q in (50, 99))
    print(f"[bench] coalesced (batch={apply_batch}, engine={engine_kind}/"
          f"{backend}): {wall:.3f}s = {fps:,.0f} frames/s "
          f"({fps / base_fps:.2f}x); visibility p50 {p50:.2f}ms "
          f"p99 {p99:.2f}ms; {node.stats.repl_coalesce_flushes} flushes, "
          f"{node.stats.repl_apply_barriers} barriers", file=sys.stderr)

    got, want = node.canonical(), base_node.canonical()
    n_diff = compare_canonical(got, want)
    verified = n_diff == 0
    print(f"[bench] verify: {'OK' if verified else 'MISMATCH'} on "
          f"{len(want)} keys ({n_diff} diffs)", file=sys.stderr)

    out = {
        "metric": "stream_apply_frames_per_sec",
        "value": round(fps, 1),
        "unit": "frames/sec",
        "mode": "stream",
        "frames": len(frames),
        "stream_keys": n_keys,
        "wall_s": round(wall, 3),
        "per_frame_baseline_fps": round(base_fps, 1),
        "vs_per_frame": round(fps / base_fps, 2),
        "visibility_p50_ms": round(p50, 3),
        "visibility_p99_ms": round(p99, 3),
        "apply_batch": apply_batch,
        "coalesce_flushes": node.stats.repl_coalesce_flushes,
        "apply_barriers": node.stats.repl_apply_barriers,
        "engine": engine_kind,
        "backend": backend,
        "verified": verified,
        "host": host_fingerprint(),
    }
    out.update(engine_counters(node.engine))
    if note:
        out["note"] = note
    eng = getattr(node, "engine", None)
    if hasattr(eng, "close"):
        eng.close()
    print(json.dumps(out))
    if not verified:
        sys.exit(1)


# --------------------------------------------------------------------------
# --mode stream --wire: the replication WIRE itself, socket to socket.
# The stream mode above replays frames straight into the applier — it
# measures the apply path with the transport already paid.  The wire
# legs run the REAL push loop against a real socket pair and a receiver
# driving the real intake, interleaved: batch wire (REPLBATCH columnar
# runs, replica/wire.py) vs per-frame wire (the pre-PR byte stream) vs
# the intra-node apply baseline, every leg oracle-verified against the
# per-frame CPU replay, with wire bytes + encode/decode split per leg —
# and a 3-node mesh differential (batch-wire nodes + one per-frame
# node) that must converge byte-identically under mixed traffic.


def frames_to_entries(frames) -> list:
    """Recorded REPLICATE frames -> (uuid, name, args) repl-log rows."""
    from constdb_tpu.resp.message import as_bytes, as_int

    return [(as_int(items[3]), as_bytes(items[4]), list(items[5:]))
            for items in frames]


def _timed_wire_codec():
    """Wrap the wire codec entry points with perf counters (bench-only:
    the product pays no timing overhead).  Returns (acc, restore)."""
    import constdb_tpu.replica.wire as wire_mod

    enc0, dec0 = wire_mod.build_wire_batch, wire_mod.decode_wire_batch
    acc = {"enc": 0.0, "dec": 0.0}

    def enc(*a, **k):
        t = time.perf_counter()
        r = enc0(*a, **k)
        acc["enc"] += time.perf_counter() - t
        return r

    def dec(*a, **k):
        t = time.perf_counter()
        r = dec0(*a, **k)
        acc["dec"] += time.perf_counter() - t
        return r

    wire_mod.build_wire_batch = enc
    wire_mod.decode_wire_batch = dec

    def restore():
        wire_mod.build_wire_batch = enc0
        wire_mod.decode_wire_batch = dec0

    return acc, restore


async def _wire_replay(entries, batching: bool, wire_batch: int,
                       apply_batch: int, latency_s: float):
    """One socket-to-socket leg: the real `_push_loop` streams a filled
    repl_log over a socketpair; the receiver drives the real intake
    (per-frame coalescer + REPLBATCH apply).  Returns the receiver
    node, wall seconds (push start -> watermark covers the last op),
    the pusher node (wire counters), and the REPLACK count."""
    import socket
    import types

    from constdb_tpu.replica.coalesce import CoalescingApplier
    from constdb_tpu.replica.link import (CAP_BATCH_STREAM, PARTSYNC,
                                          REPLACK, REPLBATCH, REPLICATE,
                                          ReplicaLink)
    from constdb_tpu.replica.manager import ReplicaMeta
    from constdb_tpu.resp.codec import make_parser
    from constdb_tpu.resp.message import as_bytes, as_int
    from constdb_tpu.server.node import Node

    loop = asyncio.get_running_loop()
    pusher = Node(node_id=99, repl_log_cap=1 << 40)
    for uuid, name, args in entries:
        pusher.repl_log.push(uuid, name, args)
    last = entries[-1][0]
    app = types.SimpleNamespace(node=pusher, heartbeat=0.2,
                                reconnect_delay=1.0, handshake_timeout=5.0,
                                work_dir=".", wire_batch=wire_batch,
                                wire_latency=0.005)
    meta = ReplicaMeta(addr="bench-wire:1")
    link = ReplicaLink(app, meta)
    link._peer_caps = CAP_BATCH_STREAM if batching else 0
    s_push, s_pull = socket.socketpair()
    push_reader, push_writer = await asyncio.open_connection(sock=s_push)
    pull_reader, pull_writer = await asyncio.open_connection(sock=s_pull)
    recv = Node(node_id=1)
    rmeta = ReplicaMeta("bench-wire:0")
    applier = CoalescingApplier(recv, rmeta, max_frames=apply_batch,
                                max_latency=latency_s, now=loop.time)
    acks = 0

    async def receiver() -> None:
        nonlocal acks
        parser = make_parser()
        while rmeta.uuid_he_sent < last:
            msg = parser.next_msg()
            if msg is None:
                if applier.pending:
                    applier.flush()  # stream idle: land now
                    continue  # re-check the watermark BEFORE blocking —
                    # a tail landed by this flush must end the leg now,
                    # not a pusher heartbeat later (which would charge
                    # an asymmetric ~0.2s penalty to the per-frame leg)
                data = await pull_reader.read(1 << 16)
                if not data:
                    raise ConnectionError("wire leg: EOF")
                parser.feed(data)
                continue
            items = msg.items
            kind = as_bytes(items[0]).lower()
            if kind == REPLICATE:
                applier.apply(items)
            elif kind == REPLBATCH:
                applier.apply_wire_batch(items)
            elif kind == REPLACK:
                acks += 1
                if len(items) > 3:
                    applier.observe_beacon(as_int(items[3]))
            elif kind != PARTSYNC:
                raise AssertionError(f"unexpected wire frame {kind!r}")

    t0 = loop.time()
    push_task = asyncio.create_task(link._push_loop(push_writer,
                                                    peer_resume=0))
    try:
        await asyncio.wait_for(receiver(), timeout=600)
        wall = loop.time() - t0
    finally:
        push_task.cancel()
        for w in (push_writer, pull_writer):
            try:
                w.close()
            except (ConnectionError, OSError):
                pass
    recv.ensure_flushed()
    return recv, wall, pusher, acks


async def _wire_mesh_differential(work_dir: str) -> dict:
    """3-node mesh, one node pinned to the per-frame wire: mixed
    write/DEL/membership traffic from every node must converge all
    three to the identical canonical export (the deterministic twin
    lives in tests/test_repl_capabilities.py)."""
    import random as _random

    from constdb_tpu.resp.codec import RespParser, encode_msg as _enc
    from constdb_tpu.resp.message import Arr, Bulk
    from constdb_tpu.server.io import start_node
    from constdb_tpu.server.node import Node

    class _Cli:
        def __init__(self):
            self.parser = RespParser()

        async def connect(self, addr):
            host, port = addr.rsplit(":", 1)
            self.reader, self.writer = await asyncio.open_connection(
                host, int(port))
            return self

        async def cmd(self, *parts):
            self.writer.write(_enc(Arr([
                Bulk(p if isinstance(p, bytes) else str(p).encode())
                for p in parts])))
            await self.writer.drain()
            while True:
                msg = self.parser.next_msg()
                if msg is not None:
                    return msg
                data = await asyncio.wait_for(self.reader.read(1 << 16), 10)
                if not data:
                    raise ConnectionError("EOF")
                self.parser.feed(data)

        async def close(self):
            self.writer.close()

    apps = []
    for i in range(3):
        node = Node(node_id=i + 1, alias=f"w{i + 1}")
        apps.append(await start_node(node, host="127.0.0.1", port=0,
                                     work_dir=work_dir, heartbeat=0.15,
                                     reconnect_delay=0.25, gc_interval=0.2))
    apps[2].wire_batch = 1  # the per-frame node, pinned pre-handshake
    out = {"converged": False, "batches": 0, "perframe_node_batches": 0}
    try:
        clients = [await _Cli().connect(a.advertised_addr) for a in apps]
        await clients[0].cmd("meet", apps[1].advertised_addr)
        await clients[0].cmd("meet", apps[2].advertised_addr)
        rng = _random.Random(31)
        for i in range(300):
            c = clients[i % 3]
            r = rng.random()
            k = f"k{rng.randrange(50)}"
            if r < 0.35:
                await c.cmd("set", "r" + k, f"v{i}")
            elif r < 0.55:
                await c.cmd("incrby", "c" + k, rng.randrange(1, 9))
            elif r < 0.75:
                await c.cmd("sadd", "s" + k, f"m{rng.randrange(12)}")
            elif r < 0.88:
                await c.cmd("hset", "h" + k, "f1", f"v{i}")
            else:
                await c.cmd("del", "r" + k)
        # pipelined burst so runs form on the capable pair
        c0 = clients[0]
        for i in range(300):
            c0.writer.write(_enc(Arr([Bulk(b"set"),
                                      Bulk(b"burst%d" % i),
                                      Bulk(b"v" * 12)])))
        await c0.writer.drain()
        got = 0
        while got < 300:
            if c0.parser.next_msg() is not None:
                got += 1
                continue
            data = await asyncio.wait_for(c0.reader.read(1 << 16), 10)
            if not data:
                raise ConnectionError("EOF")
            c0.parser.feed(data)
        deadline = asyncio.get_running_loop().time() + 60
        while asyncio.get_running_loop().time() < deadline:
            canons = [a.node.canonical() for a in apps]
            if all(c == canons[0] for c in canons[1:]):
                out["converged"] = True
                break
            await asyncio.sleep(0.05)
        out["batches"] = sum(a.node.stats.repl_wire_batches_out
                             for a in apps[:2])
        out["perframe_node_batches"] = \
            apps[2].node.stats.repl_wire_batches_out + \
            apps[2].node.stats.repl_wire_batches_in
        for c in clients:
            await c.close()
    finally:
        for a in apps:
            await a.close()
    return out


def wire_main(args) -> None:
    """`bench.py --mode stream --wire`: the batch wire protocol end to
    end over real sockets.  Emits ONE JSON line (BENCH_r14)."""
    import tempfile

    n_frames = int(os.environ.get("CONSTDB_BENCH_FRAMES", 100_000))
    n_keys = int(os.environ.get("CONSTDB_BENCH_STREAM_KEYS", 20_000))
    apply_batch = int(os.environ.get("CONSTDB_BENCH_APPLY_BATCH", 4096))
    latency_s = float(os.environ.get("CONSTDB_BENCH_APPLY_LATENCY_MS",
                                     1000.0)) / 1000.0
    wire_batch = int(os.environ.get("CONSTDB_BENCH_WIRE_BATCH", 512))
    reps = int(os.environ.get("CONSTDB_BENCH_WIRE_REPS", 3))

    ensure_native()
    if args.frame_log and os.path.exists(args.frame_log):
        frames = load_frame_log(args.frame_log)
    else:
        frames = make_frame_log(n_frames, n_keys)
        if args.frame_log:
            save_frame_log(args.frame_log, frames)
    entries = frames_to_entries(frames)
    per_frame_wire_bytes = sum(
        len(encode_msg_frame(items)) for items in frames)
    print(f"[bench] wire legs: {len(frames)} frames, per-frame wire "
          f"{per_frame_wire_bytes:,} B "
          f"({per_frame_wire_bytes / len(frames):.1f} B/op)",
          file=sys.stderr)

    # oracle: the per-frame CPU replay of the same log
    base_node, _, _ = replay_stream(frames, CpuMergeEngine,
                                    apply_batch=1, latency_s=1.0)
    want = base_node.canonical()

    # intra-node baseline: the coalesced apply path with no socket
    intra_wall = float("inf")
    for _ in range(reps):
        _, w_, _ = replay_stream(frames, CpuMergeEngine,
                                 apply_batch=apply_batch,
                                 latency_s=latency_s)
        intra_wall = min(intra_wall, w_)

    best = {True: None, False: None}
    for _ in range(reps):
        for batching in (True, False):
            acc, restore = _timed_wire_codec()
            try:
                recv, wall, pusher, acks = asyncio.run(_wire_replay(
                    entries, batching, wire_batch, apply_batch, latency_s))
            finally:
                restore()
            leg = {
                "leg": "batch-wire" if batching else "per-frame-wire",
                "wall_s": round(wall, 3),
                "fps": round(len(frames) / wall, 1),
                "wire_bytes": pusher.stats.repl_wire_bytes_out,
                "bytes_per_op": round(
                    pusher.stats.repl_wire_bytes_out / len(frames), 1),
                "batches": pusher.stats.repl_wire_batches_out,
                "batch_frames": pusher.stats.repl_wire_batch_frames_out,
                "encode_s": round(acc["enc"], 3),
                "decode_s": round(acc["dec"], 3),
                "replacks": acks,
                "coalesce_flushes": recv.stats.repl_coalesce_flushes,
                "apply_barriers": recv.stats.repl_apply_barriers,
                "wire_demotions": recv.stats.repl_wire_demotions,
                "diffs": compare_canonical(recv.canonical(), want),
            }
            prev = best[batching]
            if leg["diffs"]:
                best[batching] = leg  # a diverging rep always surfaces
            elif prev is None or (prev["diffs"] == 0
                                  and wall < prev["wall_s"]):
                best[batching] = leg
            print(f"[bench] {leg['leg']}: {leg['wall_s']}s = "
                  f"{leg['fps']:,.0f} frames/s, "
                  f"{leg['wire_bytes']:,} wire B "
                  f"({leg['bytes_per_op']} B/op), {leg['batches']} "
                  f"batches, enc {leg['encode_s']}s dec "
                  f"{leg['decode_s']}s, {leg['replacks']} acks "
                  f"({'OK' if leg['diffs'] == 0 else 'MISMATCH'})",
                  file=sys.stderr)

    batch_leg, frame_leg = best[True], best[False]
    with tempfile.TemporaryDirectory(prefix="constdb-wire-mesh") as td:
        mesh = asyncio.run(_wire_mesh_differential(td))
    print(f"[bench] mesh differential: converged={mesh['converged']}, "
          f"{mesh['batches']} batches on the capable pair, "
          f"{mesh['perframe_node_batches']} on the per-frame node",
          file=sys.stderr)

    verified = batch_leg["diffs"] == 0 and frame_leg["diffs"] == 0 and \
        mesh["converged"] and mesh["perframe_node_batches"] == 0
    out = {
        "metric": "wire_stream_apply_frames_per_sec",
        "value": batch_leg["fps"],
        "unit": "frames/sec",
        "mode": "stream-wire",
        "frames": len(frames),
        "stream_keys": n_keys,
        "wire_batch": wire_batch,
        "apply_batch": apply_batch,
        "legs": [batch_leg, frame_leg],
        "speedup_vs_per_frame_wire": round(
            batch_leg["fps"] / frame_leg["fps"], 2),
        "wire_bytes_ratio": round(
            frame_leg["wire_bytes"] / batch_leg["wire_bytes"], 2),
        "intra_node_fps": round(len(frames) / intra_wall, 1),
        "mesh_differential": mesh,
        "engine": "cpu-hostbatch",
        "backend": "none",
        "verified": verified,
        "host": host_fingerprint(),
    }
    print(json.dumps(out))
    if not verified:
        sys.exit(1)


def encode_msg_frame(items) -> bytes:
    from constdb_tpu.resp.codec import encode_msg
    from constdb_tpu.resp.message import Arr

    return encode_msg(Arr(items))


# ---------------------------------------------------------------- fan-out


async def _fanout_replay(entries, n_peers: int, cache_mb: int,
                         wire_batch: int, apply_batch: int,
                         latency_s: float, compress: bool = False):
    """One fan-out leg: ONE pusher node drives N real `_push_loop`s over
    N socketpairs into N independent receiver nodes (the broadcast
    plane's steady-state shape).  `cache_mb` sizes the encode-once run
    cache (0 = the pre-broadcast every-peer-re-encodes path).  Returns
    (recv_nodes, wall_s, pusher, per_link_rows)."""
    import socket
    import types

    from constdb_tpu.replica.coalesce import CoalescingApplier
    from constdb_tpu.replica.link import (CAP_BATCH_STREAM, CAP_COMPRESS,
                                          PARTSYNC, REPLACK, REPLBATCH,
                                          REPLICATE, ReplicaLink)
    from constdb_tpu.replica.manager import ReplicaMeta
    from constdb_tpu.resp.codec import make_parser
    from constdb_tpu.resp.message import as_bytes, as_int
    from constdb_tpu.server.node import Node

    loop = asyncio.get_running_loop()
    pusher = Node(node_id=99, repl_log_cap=1 << 40)
    pusher.wire_cache.configure(cache_mb << 20)
    for uuid, name, args in entries:
        pusher.repl_log.push(uuid, name, args)
    last = entries[-1][0]
    # repl_window=0: these receivers never REPLACK, so any finite
    # window would park the drain forever once a leg's stream bytes
    # pass it (flow control is not what this leg measures)
    app = types.SimpleNamespace(node=pusher, heartbeat=0.2,
                                reconnect_delay=1.0, handshake_timeout=5.0,
                                work_dir=".", wire_batch=wire_batch,
                                wire_latency=0.005, repl_window=0)
    caps = CAP_BATCH_STREAM | (CAP_COMPRESS if compress else 0)

    async def receiver(pull_reader, stash) -> None:
        # a real mesh's peers apply on OTHER machines: during the timed
        # window this 2-core box only pays the pusher's fan-out plus
        # minimal frame parsing (coverage detection); each captured
        # stream is applied and oracle-verified AFTER the wall stops
        parser = make_parser()
        covered = 0
        while covered < last:
            msg = parser.next_msg()
            if msg is None:
                data = await pull_reader.read(1 << 16)
                if not data:
                    raise ConnectionError("fanout leg: EOF")
                parser.feed(data)
                continue
            items = msg.items
            kind = as_bytes(items[0]).lower()
            if kind in (REPLICATE, REPLBATCH):
                covered = as_int(items[3])
                stash.append((kind, items))
            elif kind not in (REPLACK, PARTSYNC):
                raise AssertionError(f"unexpected wire frame {kind!r}")

    links, writers, recv_coros, stashes = [], [], [], []
    for i in range(n_peers):
        meta = ReplicaMeta(addr=f"bench-fan:{i}")
        pusher.replicas.peers[meta.addr] = meta
        link = ReplicaLink(app, meta)
        link._peer_caps = caps
        s_push, s_pull = socket.socketpair()
        _pr, push_writer = await asyncio.open_connection(sock=s_push)
        pull_reader, _pw = await asyncio.open_connection(sock=s_pull)
        stash: list = []
        links.append(link)
        writers.append((push_writer, _pw))
        stashes.append(stash)
        recv_coros.append(receiver(pull_reader, stash))

    t0 = loop.time()
    push_tasks = [asyncio.create_task(lk._push_loop(w[0], peer_resume=0))
                  for lk, w in zip(links, writers)]
    try:
        await asyncio.wait_for(asyncio.gather(*recv_coros), timeout=600)
        wall = loop.time() - t0
    finally:
        for t in push_tasks:
            t.cancel()
        for pw, qw in writers:
            for w in (pw, qw):
                try:
                    w.close()
                except (ConnectionError, OSError):
                    pass
    # post-wall: land every captured stream through the real intake
    recvs = []
    for i, stash in enumerate(stashes):
        recv = Node(node_id=i + 1)
        applier = CoalescingApplier(recv, ReplicaMeta(f"bench-fan-src:{i}"),
                                    max_frames=apply_batch,
                                    max_latency=latency_s, now=loop.time)
        for kind, items in stash:
            if kind == REPLICATE:
                applier.apply(items)
            else:
                applier.apply_wire_batch(items)
        applier.flush()
        recv.ensure_flushed()
        recvs.append(recv)
    per_link = [{"bytes_out": lk.bytes_out, "cache_hits": lk.cache_hits,
                 "cache_misses": lk.cache_misses,
                 "comp_raw": lk.comp_raw_bytes,
                 "comp_wire": lk.comp_wire_bytes} for lk in links]
    return recvs, wall, pusher, per_link


def _fullsync_bytes_leg(n_keys: int, n_replicas: int, engine_kind: str,
                        work_dir: str) -> dict:
    """Compressed-vs-plain bulk sync bytes: the SAME keyspace dumped as
    the plain full-sync stream (per-section zlib, the pre-CAP_COMPRESS
    wire) and as the compressed container, both loaded back into fresh
    stores and canonical()-compared byte-identically.  The workload is
    HLC-ordered (make_workload hlc_order): a real node's dump iterates
    keys in creation order, so its uuid columns are near-monotone —
    the shape the container's transposition filter exploits."""
    from constdb_tpu.persist.snapshot import (NodeMeta, batch_chunks,
                                              load_snapshot,
                                              write_snapshot_file)
    from constdb_tpu.engine.base import batch_from_keyspace

    batches = make_workload(n_keys, n_replicas, hlc_order=True)
    if engine_kind == "cpu":
        engine = CpuMergeEngine()
    else:
        from constdb_tpu.engine.tpu import TpuMergeEngine
        engine = TpuMergeEngine()
    ks = KeySpace()
    for b in batches:
        for chunk in batch_chunks(b, 1 << 16):
            engine.merge(ks, chunk)
    if getattr(engine, "needs_flush", False):
        engine.flush(ks)
    capture = batch_from_keyspace(ks)
    meta = NodeMeta(node_id=1, alias="bench")
    p_plain = os.path.join(work_dir, "fsync.plain.snapshot")
    p_comp = os.path.join(work_dir, "fsync.z.snapshot")
    # the acceptance denominator: the UNCOMPRESSED stream (level 0 —
    # what the bytes are before any compression; the pre-PR wire
    # additionally had the per-section zlib, reported as plain_bytes)
    raw_bytes = write_snapshot_file(p_plain, meta, [], [capture],
                                    compress_level=0)
    t0 = time.perf_counter()
    plain_bytes = write_snapshot_file(p_plain, meta, [], [capture],
                                      compress_level=1)
    t_plain = time.perf_counter() - t0
    t0 = time.perf_counter()
    comp_bytes = write_snapshot_file(p_comp, meta, [], [capture],
                                     container_level=6)
    t_comp = time.perf_counter() - t0
    # both variants must land IDENTICAL state (the verify half of the
    # bulk-bytes acceptance: byte-identical post-apply canonical export)
    sub_keys = subsample_keys(batches[0].keys, n_keys)
    want = ks.canonical(keys=sub_keys)
    canons = []
    for p in (p_plain, p_comp):
        ks2 = KeySpace()
        load_snapshot(p, ks2, engine=CpuMergeEngine())
        canons.append(ks2.canonical(keys=sub_keys))
    verified = canons[0] == want and canons[1] == want
    for p in (p_plain, p_comp):
        try:
            os.unlink(p)
        except OSError:
            pass
    return {
        "keys": n_keys, "replicas": n_replicas,
        "uncompressed_bytes": raw_bytes,
        "plain_bytes": plain_bytes, "compressed_bytes": comp_bytes,
        "bytes_ratio_vs_uncompressed": round(comp_bytes / raw_bytes, 4),
        "bytes_ratio_vs_plain_wire": round(comp_bytes / plain_bytes, 4),
        "plain_dump_s": round(t_plain, 3),
        "compressed_dump_s": round(t_comp, 3),
        "verified": verified,
    }


def fanout_main(args) -> None:
    """`bench.py --mode stream --peers N`: the broadcast replication
    plane — encode-once fan-out scaling (1/2/4 peers, cache-on vs
    cache-off interleaved, every peer oracle-verified) plus the
    compressed-vs-plain bulk-sync bytes leg.  Emits ONE JSON line
    (BENCH_r16)."""
    import tempfile

    n_frames = int(os.environ.get("CONSTDB_BENCH_FRAMES", 60_000))
    n_keys = int(os.environ.get("CONSTDB_BENCH_STREAM_KEYS", 20_000))
    apply_batch = int(os.environ.get("CONSTDB_BENCH_APPLY_BATCH", 4096))
    latency_s = float(os.environ.get("CONSTDB_BENCH_APPLY_LATENCY_MS",
                                     1000.0)) / 1000.0
    wire_batch = int(os.environ.get("CONSTDB_BENCH_WIRE_BATCH", 512))
    reps = int(os.environ.get("CONSTDB_BENCH_FANOUT_REPS", 2))
    cache_mb = int(os.environ.get("CONSTDB_BENCH_ENCODE_CACHE_MB", 64))
    peer_counts = [int(p) for p in os.environ.get(
        "CONSTDB_BENCH_FANOUT_PEERS", "1,2,4").split(",")]
    max_peers = args.peers
    fs_keys = int(os.environ.get("CONSTDB_BENCH_FSYNC_KEYS", 200_000))
    fs_replicas = int(os.environ.get("CONSTDB_BENCH_FSYNC_REPLICAS", 8))
    fs_engine = os.environ.get("CONSTDB_BENCH_FSYNC_ENGINE", "cpu")

    ensure_native()
    frames = make_frame_log(n_frames, n_keys)
    entries = frames_to_entries(frames)

    # oracle: the per-frame CPU replay of the same log
    base_node, _, _ = replay_stream(frames, CpuMergeEngine,
                                    apply_batch=1, latency_s=1.0)
    want = base_node.canonical()

    curve = []
    verified = True
    for peers in peer_counts:
        if peers > max_peers:
            continue
        best = {True: None, False: None}
        for _ in range(reps):
            # interleaved cache-on / cache-off so drift hits both legs
            for cache_on in (True, False):
                recvs, wall, pusher, per_link = asyncio.run(
                    _fanout_replay(entries, peers,
                                   cache_mb if cache_on else 0,
                                   wire_batch, apply_batch, latency_s))
                diffs = sum(compare_canonical(r.canonical(), want)
                            for r in recvs)
                st = pusher.stats
                hits, misses = (st.repl_encode_cache_hits,
                                st.repl_encode_cache_misses)
                leg = {
                    "peers": peers,
                    "cache": "on" if cache_on else "off",
                    "wall_s": round(wall, 3),
                    "fps_per_peer": round(n_frames / wall, 1),
                    "agg_fps": round(n_frames * peers / wall, 1),
                    "cache_hits": hits,
                    "cache_misses": misses,
                    "cache_hit_rate": round(hits / (hits + misses), 3)
                    if hits + misses else 0.0,
                    "wire_bytes": st.repl_wire_bytes_out,
                    "per_link": per_link,
                    "diffs": diffs,
                }
                prev = best[cache_on]
                if diffs:
                    best[cache_on] = leg
                elif prev is None or (prev["diffs"] == 0
                                      and wall < prev["wall_s"]):
                    best[cache_on] = leg
                print(f"[bench] fanout peers={peers} cache="
                      f"{leg['cache']}: {leg['wall_s']}s = "
                      f"{leg['agg_fps']:,.0f} agg frames/s, hit rate "
                      f"{leg['cache_hit_rate']}, "
                      f"{'OK' if diffs == 0 else 'MISMATCH'}",
                      file=sys.stderr)
        on, off = best[True], best[False]
        verified &= on["diffs"] == 0 and off["diffs"] == 0
        curve.append({"peers": peers, "cache_on": on, "cache_off": off,
                      "speedup_vs_cache_off": round(
                          on["agg_fps"] / off["agg_fps"], 2)})

    print(f"[bench] fullsync bytes leg: {fs_keys} keys x {fs_replicas} "
          f"replicas ({fs_engine})", file=sys.stderr)
    with tempfile.TemporaryDirectory(prefix="constdb-fanout") as td:
        fullsync = _fullsync_bytes_leg(fs_keys, fs_replicas, fs_engine, td)
    verified &= fullsync["verified"]
    print(f"[bench] fullsync bytes: uncompressed "
          f"{fullsync['uncompressed_bytes']:,} / plain wire "
          f"{fullsync['plain_bytes']:,} -> compressed "
          f"{fullsync['compressed_bytes']:,} "
          f"({fullsync['bytes_ratio_vs_uncompressed']:.3f}x of "
          f"uncompressed, {fullsync['bytes_ratio_vs_plain_wire']:.3f}x "
          f"of the plain wire), verified={fullsync['verified']}",
          file=sys.stderr)

    top = curve[-1]
    out = {
        "metric": "fanout_aggregate_frames_per_sec",
        "value": top["cache_on"]["agg_fps"],
        "unit": "frames/sec",
        "mode": "stream-fanout",
        "frames": n_frames,
        "stream_keys": n_keys,
        "wire_batch": wire_batch,
        "apply_batch": apply_batch,
        "encode_cache_mb": cache_mb,
        "curve": curve,
        "fanout_speedup_at_max_peers": top["speedup_vs_cache_off"],
        "cache_hit_rate_at_max_peers": top["cache_on"]["cache_hit_rate"],
        "fullsync": fullsync,
        "engine": "cpu-hostbatch",
        "backend": "none",
        "verified": verified,
        "host": host_fingerprint(),
    }
    print(json.dumps(out))
    if not verified:
        sys.exit(1)


# --------------------------------------------------------------------------
# --mode tensor: tensor-valued registers — the first family designed
# device-first (crdt/tensor.py).  A stream of contribution micro-batches
# (the coalescer flush shape: a few hundred rows, rows_unique=False)
# merges into a store, and EVERY round the full key set is read back
# (the aggregation product — distributed model/embedding serving).  The
# device leg keeps payloads resident (engine/tpu.py pools: merges
# scatter in place, reads gather+reduce on device, only [G, K] results
# download); the host leg is the per-row reference
# (KeySpace.tensor_merge_row + tensor_read).  Both legs are
# oracle-verified bit-identical — the canonical-order law makes that a
# hard equality even for float reductions.


def make_tensor_workload(n_rounds: int, batch_rows: int, n_keys: int,
                         n_nodes: int, elems: int, strat: str,
                         seed: int = 17) -> list:
    """Deterministic per-round ColumnarBatches of tensor contributions
    (every (key, node) slot seeded in round 0 so reads always see
    n_nodes contributors — the model-merge shape)."""
    from constdb_tpu.crdt import semantics as S
    from constdb_tpu.crdt import tensor as T
    from constdb_tpu.engine.base import ColumnarBatch

    rng = np.random.default_rng(seed)
    meta = T.TensorMeta(T.STRATEGY_IDS[strat], 0, (elems,))
    cfg = T.pack_config(meta)
    u = 1
    out = []
    for r in range(n_rounds):
        if r == 0:
            pairs = [(k, nd) for k in range(n_keys)
                     for nd in range(1, n_nodes + 1)]
        else:
            pairs = [(int(rng.integers(n_keys)),
                      int(rng.integers(1, n_nodes + 1)))
                     for _ in range(batch_rows)]
        n = len(pairs)
        b = ColumnarBatch()
        b.keys = [b"t%06d" % k for k, _ in pairs]
        uuids = np.empty(n, dtype=_I64)
        for i in range(n):
            u += 1
            uuids[i] = (MS0 + u) << SEQ_BITS
        b.key_enc = np.full(n, S.ENC_TENSOR, np.int8)
        b.key_ct = uuids.copy()
        b.key_mt = uuids.copy()
        b.key_dt = np.zeros(n, dtype=_I64)
        b.key_expire = np.zeros(n, dtype=_I64)
        b.reg_val = [None] * n
        b.reg_t = np.zeros(n, dtype=_I64)
        b.reg_node = np.zeros(n, dtype=_I64)
        b.tns_ki = np.arange(n, dtype=_I64)
        b.tns_node = np.fromiter((nd for _, nd in pairs), dtype=_I64,
                                 count=n)
        b.tns_uuid = uuids
        b.tns_cnt = rng.integers(1, 8, size=n).astype(_I64)
        b.tns_cfg = [cfg] * n
        payloads = (rng.standard_normal((n, elems)) * 4).astype(np.float32)
        b.tns_payload = [payloads[i].tobytes() for i in range(n)]
        b.rows_unique_per_slot = False
        out.append(b)
    return out


def _tensor_leg(batches, n_keys: int, make_engine, device_reads: bool):
    """One leg: merge every round's batch, then read ALL keys (device
    path via engine.tensor_read_many when available).  Returns (store,
    engine, wall_s, final reads dict key->bytes)."""
    from constdb_tpu.store.keyspace import KeySpace

    store = KeySpace()
    engine = make_engine()
    reads = None
    t0 = time.perf_counter()
    for b in batches:
        engine.merge_many(store, [b])
        kids = range(n_keys)
        if device_reads:
            reads = engine.tensor_read_many(store, kids)
        else:
            reads = {kid: store.tensor_read(kid) for kid in kids}
    if getattr(engine, "needs_flush", False):
        engine.flush(store)
    wall = time.perf_counter() - t0
    final = {store.key_bytes[kid]: (None if arr is None else arr.tobytes())
             for kid, arr in reads.items()}
    return store, engine, wall, final


def tensor_main(args) -> None:
    """`bench.py --mode tensor`: the resident device tensor path vs the
    host reference on coalescer-sized micro-batches, interleaved
    best-of-3 per strategy, both legs oracle-verified bit-identical
    (final reads AND canonical export).  Emits ONE JSON line
    (BENCH_r13)."""
    from constdb_tpu.engine.tpu import TpuMergeEngine
    from constdb_tpu.utils.backend import force_cpu_platform, probe_backend

    n_keys = int(os.environ.get("CONSTDB_BENCH_TNS_KEYS", 128))
    elems = int(os.environ.get("CONSTDB_BENCH_TNS_ELEMS", 4096))
    n_nodes = int(os.environ.get("CONSTDB_BENCH_TNS_NODES", 8))
    n_rounds = int(os.environ.get("CONSTDB_BENCH_TNS_ROUNDS", 24))
    batch_rows = int(os.environ.get("CONSTDB_BENCH_TNS_BATCH", 128))
    strats = os.environ.get("CONSTDB_BENCH_TNS_STRATS",
                            "avg,maxmag,trimmed-mean,sum,lww").split(",")
    reps = int(os.environ.get("CONSTDB_BENCH_TNS_REPS", 3))
    fold = os.environ.get("CONSTDB_BENCH_FOLD", "auto")

    probe = probe_backend()
    note = ""
    if not probe.ok:
        note = (f"device backend unavailable ({probe.error}); "
                "XLA-on-CPU fallback")
        print(f"[bench] WARNING: {note}", file=sys.stderr)
        force_cpu_platform()
    import jax
    backend = jax.default_backend()

    curve = []
    verified = True
    for strat in strats:
        batches = make_tensor_workload(n_rounds, batch_rows, n_keys,
                                       n_nodes, elems, strat)
        rows_total = sum(len(b.tns_ki) for b in batches)
        best_dev = (float("inf"), None, None, None)
        best_host = (float("inf"), None, None)
        for _ in range(reps):
            st_d, eng_d, w_d, reads_d = _tensor_leg(
                batches, n_keys,
                # steady FORCED: this leg measures the resident path
                # itself; 'auto' keeps CPU-only production boxes on the
                # host strategy (the host leg below IS that path)
                lambda: TpuMergeEngine(resident=True, steady=True,
                                       warmup=0, dense_fold=fold),
                device_reads=True)
            if w_d < best_dev[0]:
                if best_dev[2] is not None:
                    best_dev[2].close()  # displaced best: free its pools
                best_dev = (w_d, st_d, eng_d, reads_d)
            elif hasattr(eng_d, "close"):
                eng_d.close()
            st_h, _eng_h, w_h, reads_h = _tensor_leg(
                batches, n_keys, CpuMergeEngine, device_reads=False)
            if w_h < best_host[0]:
                best_host = (w_h, st_h, reads_h)
        w_d, st_d, eng_d, reads_d = best_dev
        w_h, st_h, reads_h = best_host
        ok = reads_d == reads_h and \
            st_d.canonical() == st_h.canonical()
        verified = verified and ok
        leg = {
            "strategy": strat,
            "dev_wall_s": round(w_d, 3),
            "host_wall_s": round(w_h, 3),
            "dev_rows_per_sec": round(rows_total / w_d, 1),
            "host_rows_per_sec": round(rows_total / w_h, 1),
            "speedup": round(w_h / w_d, 2),
            "rows": rows_total,
            "reads": n_rounds * n_keys,
            "verified": ok,
        }
        leg.update(engine_counters(eng_d))
        leg["tns_dev_rows"] = getattr(eng_d, "tns_dev_rows", 0)
        leg["tns_host_rows"] = getattr(eng_d, "tns_host_rows", 0)
        curve.append(leg)
        print(f"[bench] tensor {strat}: device {w_d:.3f}s vs host "
              f"{w_h:.3f}s = {leg['speedup']:.2f}x "
              f"({rows_total} rows, {leg['reads']} reads, "
              f"{eng_d.tns_dev_rows} dev rows, "
              f"{leg['dev_rounds_resident']} resident rounds) "
              f"({'OK' if ok else 'MISMATCH'})", file=sys.stderr)
        if hasattr(eng_d, "close"):
            eng_d.close()
    ratios = [leg["speedup"] for leg in curve]
    out = {
        "metric": "tensor_merge_speedup_vs_host",
        "value": round(min(ratios), 2),
        "unit": "x (worst strategy)",
        "mode": "tensor",
        "keys": n_keys,
        "elems": elems,
        "payload_bytes": elems * 4,
        "contributors": n_nodes,
        "rounds": n_rounds,
        "batch_rows": batch_rows,
        "curve": curve,
        "backend": backend,
        "fold": fold,
        "verified": verified,
        "host": host_fingerprint(),
    }
    if note:
        out["note"] = note
    print(json.dumps(out))
    if not verified:
        sys.exit(1)


# --------------------------------------------------------------------------
# --mode serve: pipelined client serving over real sockets (the serve
# coalescer, server/serve.py) vs the CONSTDB_SERVE_BATCH=1 per-command
# baseline — the serving-throughput headline the r05-r08 trajectory
# (ingest, shards, stream) was still missing.


def serve_workload(conn_id: int, n_ops: int, n_keys: int, pipeline: int,
                   seed: int = 13) -> list:
    """Pre-encoded pipelined chunks for one connection: a write-heavy
    mixed command stream (sets, counters, set/hash members) with reads
    and DELs sprinkled in as serve-path barriers.  Keys carry the
    connection id, so each key has a single writer and both reply
    streams and final per-key values are interleave-invariant — the
    cross-leg oracle needs that, because two legs schedule the
    connections differently."""
    import random

    from constdb_tpu.resp.codec import encode_into
    from constdb_tpu.resp.message import Arr, Bulk

    rng = random.Random(seed * 1000 + conn_id)
    pfx = b"c%d:" % conn_id
    chunks = []
    cur = bytearray()
    n = 0
    for i in range(n_ops):
        r = rng.random()
        k = pfx + b"%05d" % rng.randrange(n_keys)
        if r < 0.25:
            body = (b"set", b"r" + k, b"v%08d" % i)
        elif r < 0.50:
            body = (b"incr", b"c" + k, b"%d" % rng.randrange(1, 100))
        elif r < 0.75:
            # tag/follower-list writes (multi-member, the set shape the
            # stream bench uses)
            body = (b"sadd", b"s" + k,
                    *(b"m%03d" % rng.randrange(256) for _ in range(8)))
        elif r < 0.95:
            # YCSB's canonical user-record workload writes 10 fields/op
            fv = []
            for f in range(10):
                fv += [b"f%02d" % rng.randrange(32), b"v%07d%d" % (i, f)]
            body = (b"hset", b"h" + k, *fv)
        elif r < 0.97:
            body = (b"get", b"r" + k)        # read barrier
        elif r < 0.995:
            body = (b"srem", b"s" + k, b"m%03d" % rng.randrange(256))
        else:
            # DELs ~0.5%, the r08 stream-bench convention: ConstDB's
            # serving workload is write-once constant data (PAPER.md) —
            # deletes are administrative, but must be PRESENT so the
            # bench exercises the flushing-barrier machinery for real
            body = (b"del", b"r" + k)        # read-modify barrier
        encode_into(cur, Arr([Bulk(b) for b in body]))
        n += 1
        if n >= pipeline:
            chunks.append((bytes(cur), n))
            cur = bytearray()
            n = 0
    if n:
        chunks.append((bytes(cur), n))
    return chunks


def _serve_bench_server(pipe, serve_batch: int, engine_kind: str,
                        serve_shards: int = 1, aof_policy=None,
                        aof_dir: str = "", read_cache_mb=None) -> None:
    """Forked server worker: one real ServerApp on a fresh port.  Sends
    the port up, serves until the parent says stop, then ships back the
    canonical export + serve stats.  `serve_shards > 1` runs the
    shard-per-core plane (server/serve_shards.py) — the canonical
    export then consolidates the worker shards."""
    import asyncio
    import gc

    from constdb_tpu.server.io import start_node
    from constdb_tpu.server.node import Node

    # redis-style serving GC posture, identical for BOTH legs: the boot
    # object graph is frozen out of collection and the gen0 threshold
    # raised so steady-state allocation churn (parsed frames, replies,
    # repl entries) is not swept every ~700 allocations
    gc.collect()
    gc.freeze()
    gc.set_threshold(100_000, 50, 50)

    if read_cache_mb is not None:
        # before Node construction — the cache cap is read from the
        # registry at init (cache-on/cache-off sub-legs)
        os.environ["CONSTDB_READ_CACHE_MB"] = str(read_cache_mb)

    def make_engine():
        if engine_kind == "cpu":
            from constdb_tpu.engine.cpu import CpuMergeEngine
            return CpuMergeEngine()
        from constdb_tpu.conf import build_engine
        return build_engine(engine_kind)

    async def main():
        node = Node(node_id=1, alias="bench", engine=make_engine())
        kw = {}
        if aof_policy is not None:
            kw = dict(aof=True, aof_fsync=aof_policy, aof_dir=aof_dir)
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir="/tmp", serve_batch=serve_batch,
                               serve_shards=serve_shards, **kw)
        pipe.send(app.port)
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, pipe.recv)  # block until "stop"
        node.ensure_flushed()
        if node.serve_plane is not None:
            canon = await node.serve_plane.canonical()
        else:
            canon = node.canonical()
        st = node.stats
        x = st.extra
        pipe.send((canon, {
            "serve_msgs_coalesced": st.serve_msgs_coalesced,
            "serve_flushes": st.serve_flushes,
            "serve_barriers": st.serve_barriers,
            "serve_reads_coalesced": st.serve_reads_coalesced,
            "serve_read_flushes": st.serve_read_flushes,
            "read_cache_hits": node.read_cache.hits,
            "read_cache_misses": node.read_cache.misses,
            "read_cache_bytes": node.read_cache.bytes,
            "read_cache_invalidations": node.read_cache.invalidations,
            "cmds_processed": st.cmds_processed,
            "native_intake_chunks": st.native_intake_chunks,
            "native_intake_msgs": st.native_intake_msgs,
            "oom_shed_writes": st.oom_shed_writes,
            "oom_hard_reclaims": st.oom_hard_reclaims,
            "used_memory": node.governor.used_memory(),
            "overload_state": node.governor.state_name,
            "serve_shards": serve_shards,
            "serve_xshard_barriers": x.get("serve_xshard_barriers", 0),
            "per_shard": {
                s: {"msgs": x.get(f"serve_shard{s}_msgs", 0),
                    "flushes": x.get(f"serve_shard{s}_flushes", 0),
                    "barriers": x.get(f"serve_shard{s}_barriers", 0),
                    "keys": x.get(f"serve_shard{s}_keys", 0)}
                for s in range(serve_shards)} if serve_shards > 1 else {},
            "aof_size_bytes": node.oplog.size_bytes()
            if node.oplog is not None else 0,
            "aof_fsyncs": node.oplog.fsyncs
            if node.oplog is not None else 0,
            "aof_encoded_batches": node.oplog.encoded_batches
            if node.oplog is not None else 0,
        }))
        await app.close()

    try:
        asyncio.run(main())
    except BaseException as e:  # parent surfaces the failure
        try:
            pipe.send(e)
        except OSError:
            pass
    finally:
        pipe.close()


def strip_canonical_times(canon: dict) -> dict:
    """Visible-value projection of a canonical export.  Two serve-bench
    legs schedule connections differently, so HLC timestamps (and
    therefore the raw canonical bytes) legitimately differ — but with
    single-writer keys every VISIBLE value is interleave-invariant, so
    this projection must match exactly."""
    from constdb_tpu.crdt import semantics as S

    out = {}
    for key, (enc, ct, mt, dt, expire, content) in canon.items():
        alive = ct >= dt
        if enc == S.ENC_COUNTER:
            val = sum(t - b for _n, t, _u, b, _bt in content)
        elif enc == S.ENC_BYTES:
            val = content[0]
        else:
            val = frozenset((m, v) for m, at, _an, dlt, v in content
                            if at >= dlt)
        out[key] = (enc, alive, val)
    return out


async def _serve_drive(port: int, per_conn: list, rtts: list,
                       hashes: list) -> None:
    """Drive every connection FULLY PIPELINED: a writer task streams the
    pre-encoded windows continuously (bounded only by socket
    backpressure — the server reads as deep a chunk as TCP delivers,
    which is what lets its planner build long runs), while a reader task
    concurrently counts replies and hashes the reply byte stream.
    Reply latency is sampled per window: send time vs the time the
    window's last reply is parsed (includes pipeline queueing — the
    latency a streaming client actually observes)."""
    import asyncio
    import hashlib
    from collections import deque

    from constdb_tpu.resp.codec import make_parser

    inflight_cap = int(os.environ.get("CONSTDB_BENCH_SERVE_INFLIGHT", 2048))

    async def one(chunks, sink, digest):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        parser = make_parser()
        clock = time.perf_counter
        marks: deque = deque()  # (cumulative reply count, send ts)
        total = sum(n for _, n in chunks)
        got = 0
        progressed = asyncio.Event()

        async def pump():
            sent = 0
            for data, n in chunks:
                # bounded in-flight window: keeps the pipeline deep
                # enough to saturate the server without the unbounded
                # queueing that would turn reply latency into a pure
                # benchmark artifact
                while sent - got > inflight_cap:
                    progressed.clear()
                    await progressed.wait()
                sent += n
                marks.append((sent, clock()))
                writer.write(data)
                await writer.drain()

        ptask = asyncio.ensure_future(pump())
        try:
            while got < total:
                b = await reader.read(1 << 16)
                if not b:
                    raise ConnectionError("server EOF")
                digest.update(b)
                parser.feed(b)
                while parser.next_msg() is not None:
                    got += 1
                progressed.set()
                now = clock()
                while marks and marks[0][0] <= got:
                    sink.append(now - marks.popleft()[1])
            await ptask
        finally:
            ptask.cancel()
            writer.close()

    digests = [hashlib.sha256() for _ in per_conn]
    sinks = [[] for _ in per_conn]
    await asyncio.gather(*(one(c, s, d) for c, s, d
                           in zip(per_conn, sinks, digests)))
    for s in sinks:
        rtts.extend(s)
    hashes.extend(d.hexdigest() for d in digests)


def _serve_leg(serve_batch: int, engine_kind: str, per_conn: list,
               serve_shards: int = 1, aof_policy=None, aof_dir: str = "",
               read_cache_mb=None):
    """One full serve-bench leg: fork a server, drive the workload,
    collect (wall_s, rtts, reply_hashes, canonical, server_stats)."""
    import asyncio
    import multiprocessing as mp

    ctx = mp.get_context("fork")
    parent, child = ctx.Pipe()
    # a shard-serving leg spawns its own worker children, which a
    # daemonic process may not — those legs run non-daemonic with an
    # explicit terminate guard instead
    p = ctx.Process(target=_serve_bench_server,
                    args=(child, serve_batch, engine_kind, serve_shards,
                          aof_policy, aof_dir, read_cache_mb),
                    daemon=serve_shards <= 1)
    p.start()
    child.close()
    try:
        port = parent.recv()
        if isinstance(port, BaseException):
            raise port
        rtts: list = []
        hashes: list = []
        t0 = time.perf_counter()
        asyncio.run(_serve_drive(port, per_conn, rtts, hashes))
        wall = time.perf_counter() - t0
        parent.send("stop")
        result = parent.recv()
        p.join()
        parent.close()
        if isinstance(result, BaseException):
            raise result
    except BaseException:
        if p.is_alive():
            p.terminate()
            p.join(timeout=5)
        raise
    canon, stats = result
    return wall, rtts, hashes, canon, stats


def serve_main(args) -> None:
    """`bench.py --mode serve`: coalesced pipelined client serving vs the
    exact per-command path (CONSTDB_SERVE_BATCH=1), same deterministic
    workload over real sockets, interleaved best-of-N, oracle-compared
    (reply streams per connection + visible-value export).  Emits ONE
    JSON line with requests/s and p50/p99 pipeline-window reply
    latency."""
    n_ops = int(os.environ.get("CONSTDB_BENCH_SERVE_OPS", 200_000))
    n_conns = int(os.environ.get("CONSTDB_BENCH_SERVE_CONNS", 4))
    pipeline = int(os.environ.get("CONSTDB_BENCH_SERVE_PIPELINE", 64))
    n_keys = int(os.environ.get("CONSTDB_BENCH_SERVE_KEYS", 2000))
    serve_batch = int(os.environ.get("CONSTDB_BENCH_SERVE_BATCH", 512))
    engine_kind = os.environ.get("CONSTDB_BENCH_SERVE_ENGINE", "cpu")
    reps = int(os.environ.get("CONSTDB_BENCH_SERVE_REPS", 2))

    ensure_native()
    per_ops = n_ops // n_conns
    t0 = time.perf_counter()
    per_conn = [serve_workload(ci, per_ops, n_keys, pipeline)
                for ci in range(n_conns)]
    total = per_ops * n_conns
    print(f"[bench] serve workload: {total} ops over {n_conns} conns x "
          f"{pipeline}-deep pipelines ({time.perf_counter() - t0:.1f}s gen)",
          file=sys.stderr)

    best = {True: None, False: None}  # coalesced? -> leg result
    for rep in range(reps):
        for coalesced in (True, False):
            leg = _serve_leg(serve_batch if coalesced else 1,
                             engine_kind, per_conn)
            tag = f"serve_batch={serve_batch if coalesced else 1}"
            print(f"[bench] rep {rep + 1} {tag}: {leg[0]:.3f}s = "
                  f"{total / leg[0]:,.0f} req/s", file=sys.stderr)
            if best[coalesced] is None or leg[0] < best[coalesced][0]:
                best[coalesced] = leg
    wall, rtts, hashes, canon, stats = best[True]
    bwall, _brtts, bhashes, bcanon, bstats = best[False]
    rps = total / wall
    base_rps = total / bwall
    lat_ms = np.asarray(rtts) * 1000.0
    p50, p99 = (float(np.percentile(lat_ms, q)) for q in (50, 99))

    replies_ok = hashes == bhashes
    export_ok = strip_canonical_times(canon) == strip_canonical_times(bcanon)
    verified = replies_ok and export_ok
    print(f"[bench] coalesced: {rps:,.0f} req/s vs per-command "
          f"{base_rps:,.0f} req/s = {rps / base_rps:.2f}x; reply-window "
          f"p50 {p50:.2f}ms p99 {p99:.2f}ms; "
          f"{stats['serve_msgs_coalesced']} coalesced / "
          f"{stats['serve_flushes']} flushes / "
          f"{stats['serve_barriers']} barriers", file=sys.stderr)
    print(f"[bench] verify: replies {'OK' if replies_ok else 'MISMATCH'} "
          f"({len(hashes)} conns), export "
          f"{'OK' if export_ok else 'MISMATCH'} ({len(canon)} keys)",
          file=sys.stderr)

    out = {
        "metric": "serve_requests_per_sec",
        "value": round(rps, 1),
        "unit": "requests/sec",
        "mode": "serve",
        "ops": total,
        "conns": n_conns,
        "pipeline": pipeline,
        "wall_s": round(wall, 3),
        "per_command_baseline_rps": round(base_rps, 1),
        "vs_per_command": round(rps / base_rps, 2),
        "reply_p50_ms": round(p50, 3),
        "reply_p99_ms": round(p99, 3),
        "serve_batch": serve_batch,
        "serve_msgs_coalesced": stats["serve_msgs_coalesced"],
        "serve_flushes": stats["serve_flushes"],
        "serve_barriers": stats["serve_barriers"],
        "engine": engine_kind,
        "verified": verified,
        "host": host_fingerprint(),
    }
    print(json.dumps(out))
    if not verified:
        sys.exit(1)


def serve_read_workload(conn_id: int, n_ops: int, n_keys: int,
                        pipeline: int, read_pct: int,
                        seed: int = 17) -> list:
    """Pre-encoded pipelined chunks for one connection at a given
    read percentage: reads hit a HOT subset of this connection's own
    single-writer keys (the canonical cache-serving shape — and what
    keeps both reply streams and final per-key values
    interleave-invariant for the cross-leg oracle), spread across every
    planned read kind; writes keep the serve-workload mix so
    invalidation is exercised for real."""
    import random

    from constdb_tpu.resp.codec import encode_into
    from constdb_tpu.resp.message import Arr, Bulk

    rng = random.Random(seed * 1000 + conn_id)
    pfx = b"c%d:" % conn_id
    rfrac = read_pct / 100.0
    # every key is seeded (4 ops each), so clamp the universe to keep
    # the seeding preamble under ~25% of the op budget (smoke-sized
    # runs shrink the keyspace instead of starving the steady state)
    n_keys = max(8, min(n_keys, n_ops // 16))
    hot = max(8, n_keys // 50)
    chunks = []
    cur = bytearray()
    n = 0
    ops = []
    # seeding preamble: populate EVERY key's families first, so the
    # read-heavy steady state reads DATA, not absence — a cache serving
    # millions of users reads keys that exist, on the cold tail too
    # (cold sets/hashes get a smaller footprint than the hot ones)
    for kid in range(n_keys):
        k = pfx + b"%05d" % kid
        step = 3 if kid < hot else 13
        ops.append((b"set", b"r" + k, b"v%08d" % kid))
        ops.append((b"sadd", b"s" + k,
                    *(b"m%03d" % m for m in range(0, 64, step))))
        fv = []
        for f in range(10 if kid < hot else 3):
            fv += [b"f%02d" % f, b"v%06d" % (kid * 10 + f)]
        ops.append((b"hset", b"h" + k, *fv))
        ops.append((b"incr", b"c" + k, b"%d" % (kid + 1)))
    for body in ops:
        encode_into(cur, Arr([Bulk(b) for b in body]))
        n += 1
        if n >= pipeline:
            chunks.append((bytes(cur), n))
            cur = bytearray()
            n = 0
    for i in range(max(0, n_ops - len(ops))):
        kid = rng.randrange(hot) if rng.random() < 0.85 \
            else rng.randrange(n_keys)
        k = pfx + b"%05d" % kid
        if rng.random() < rfrac:
            q = rng.random()
            if q < 0.40:
                body = (b"get", b"r" + k)
            elif q < 0.55:
                body = (b"smembers", b"s" + k)
            elif q < 0.65:
                body = (b"scnt", b"s" + k)
            elif q < 0.75:
                body = (b"sismember", b"s" + k,
                        b"m%03d" % rng.randrange(64))
            elif q < 0.85:
                body = (b"hget", b"h" + k, b"f%02d" % rng.randrange(10))
            elif q < 0.93:
                body = (b"hgetall", b"h" + k)
            else:
                body = (b"get", b"c" + k)   # counter read
        else:
            q = rng.random()
            if q < 0.35:
                body = (b"set", b"r" + k, b"v%08d" % i)
            elif q < 0.55:
                body = (b"incr", b"c" + k, b"%d" % rng.randrange(1, 100))
            elif q < 0.80:
                body = (b"sadd", b"s" + k,
                        *(b"m%03d" % rng.randrange(64) for _ in range(4)))
            else:
                fv = []
                for f in range(4):
                    fv += [b"f%02d" % rng.randrange(10),
                           b"v%06d%d" % (i, f)]
                body = (b"hset", b"h" + k, *fv)
        encode_into(cur, Arr([Bulk(b) for b in body]))
        n += 1
        if n >= pipeline:
            chunks.append((bytes(cur), n))
            cur = bytearray()
            n = 0
    if n:
        chunks.append((bytes(cur), n))
    return chunks


def serve_read_main(args) -> None:
    """`bench.py --mode serve --read-pct 90[,50]`: the read-heavy
    serving legs (round 18).  For each read percentage, three
    interleaved best-of-N legs on the same deterministic workload over
    real sockets — coalesced+cache, coalesced with the cache disabled,
    and the CONSTDB_SERVE_BATCH=1 per-command baseline — with the
    reply-hash + timestamp-stripped-export oracle across ALL legs (a
    stale cached reply is an oracle mismatch, not a slowdown).  Emits
    one JSON line (BENCH_r18.json) with the per-pct curve and host
    fingerprint."""
    n_ops = int(os.environ.get("CONSTDB_BENCH_SERVE_OPS", 200_000))
    n_conns = int(os.environ.get("CONSTDB_BENCH_SERVE_CONNS", 4))
    pipeline = int(os.environ.get("CONSTDB_BENCH_SERVE_PIPELINE", 64))
    # smaller default universe than the write-heavy mode: every key is
    # seeded (the cold tail reads DATA, not absence), so the universe
    # bounds the seeding preamble's share of the measured ops
    n_keys = int(os.environ.get("CONSTDB_BENCH_SERVE_KEYS", 1000))
    serve_batch = int(os.environ.get("CONSTDB_BENCH_SERVE_BATCH", 512))
    engine_kind = os.environ.get("CONSTDB_BENCH_SERVE_ENGINE", "cpu")
    reps = int(os.environ.get("CONSTDB_BENCH_SERVE_REPS", 2))
    cache_mb = int(os.environ.get("CONSTDB_BENCH_READ_CACHE_MB", 16))
    pcts = [int(p) for p in str(args.read_pct).split(",")]

    ensure_native()
    per_ops = n_ops // n_conns
    total = per_ops * n_conns
    curve = []
    verified = True
    for pct in pcts:
        per_conn = [serve_read_workload(ci, per_ops, n_keys, pipeline,
                                        pct) for ci in range(n_conns)]
        print(f"[bench] read-pct {pct}: {total} ops over {n_conns} "
              f"conns x {pipeline}-deep pipelines", file=sys.stderr)
        # leg key -> (serve_batch, read_cache_mb)
        legs = {"cache": (serve_batch, cache_mb),
                "nocache": (serve_batch, 0),
                "percmd": (1, 0)}
        best: dict = {k: None for k in legs}
        for rep in range(reps):
            for name, (sb, mb) in legs.items():
                leg = _serve_leg(sb, engine_kind, per_conn,
                                 read_cache_mb=mb)
                print(f"[bench] rep {rep + 1} {pct}r {name}: "
                      f"{leg[0]:.3f}s = {total / leg[0]:,.0f} req/s",
                      file=sys.stderr)
                if best[name] is None or leg[0] < best[name][0]:
                    best[name] = leg
        ref = best["percmd"]
        ref_strip = strip_canonical_times(ref[3])
        entry = {"read_pct": pct}
        ok_all = True
        for name in legs:
            wall, rtts, hashes, canon, stats = best[name]
            ok = hashes == ref[2] and \
                strip_canonical_times(canon) == ref_strip
            ok_all = ok_all and ok
            lat_ms = np.asarray(rtts) * 1000.0
            entry[name] = {
                "rps": round(total / wall, 1),
                "wall_s": round(wall, 3),
                "reply_p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
                "reply_p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
                "serve_reads_coalesced": stats["serve_reads_coalesced"],
                "serve_read_flushes": stats["serve_read_flushes"],
                "read_cache_hits": stats["read_cache_hits"],
                "read_cache_misses": stats["read_cache_misses"],
                "read_cache_bytes": stats["read_cache_bytes"],
                "read_cache_invalidations":
                    stats["read_cache_invalidations"],
                "replies_ok": hashes == ref[2],
            }
        entry["speedup_vs_percmd"] = round(
            entry["cache"]["rps"] / entry["percmd"]["rps"], 2)
        entry["speedup_nocache_vs_percmd"] = round(
            entry["nocache"]["rps"] / entry["percmd"]["rps"], 2)
        hits = entry["cache"]["read_cache_hits"]
        probes = hits + entry["cache"]["read_cache_misses"]
        entry["cache_hit_rate"] = round(hits / probes, 3) if probes else 0.0
        entry["verified"] = ok_all
        verified = verified and ok_all
        print(f"[bench] read-pct {pct}: cache {entry['cache']['rps']:,.0f}"
              f" / nocache {entry['nocache']['rps']:,.0f} / per-command "
              f"{entry['percmd']['rps']:,.0f} req/s = "
              f"{entry['speedup_vs_percmd']}x (hit rate "
              f"{entry['cache_hit_rate']}); oracle "
              f"{'OK' if ok_all else 'MISMATCH'}", file=sys.stderr)
        curve.append(entry)

    out = {
        "metric": "serve_read_requests_per_sec",
        "value": curve[0]["cache"]["rps"],
        "unit": "requests/sec",
        "mode": "serve-read",
        "host_note": "burstable 1-core box: client and server share the "
                     "core, so CPU-credit state swings the 90:10 ratio "
                     "1.76-2.11x across invocations of this exact "
                     "interleaved best-of-N leg (all oracle-verified); "
                     "a box with dedicated cores isolates the server-side "
                     "win from the shared client cost",
        "ops": total,
        "conns": n_conns,
        "pipeline": pipeline,
        "serve_batch": serve_batch,
        "read_cache_mb": cache_mb,
        "curve": curve,
        "engine": engine_kind,
        "verified": verified,
        "host": host_fingerprint(),
    }
    print(json.dumps(out))
    if not verified:
        sys.exit(1)


def tracked_workload(ci: int, n_clients: int, per_ops: int, n_keys: int,
                     hot: int, seed: int = 0xC0FFEE) -> list:
    """Deterministic per-client schedule for the tracked-caching legs
    (round 22): 10% writes / 90% reads, with 90% of reads hammering the
    `hot` head of the universe (the skew that makes a near-cache earn
    its keep) and the tail uniform.  Writes are SINGLE-WRITER: client
    `ci` only ever sets keys where `idx % n_clients == ci`, each with a
    per-key serial — so the final visible value of every key is
    schedule-determined and the stripped canonical export must match
    exactly across legs (same oracle as the serve modes)."""
    import random

    rng = random.Random((seed << 8) | ci)
    owned = [i for i in range(n_keys) if i % n_clients == ci]
    serial: dict = {}
    sched = []
    for _ in range(per_ops):
        r = rng.random()
        if r < 0.10:
            idx = owned[rng.randrange(len(owned))]
            serial[idx] = serial.get(idx, 0) + 1
            sched.append((b"set", b"trk:%d" % idx,
                          b"c%d:%d" % (ci, serial[idx])))
        elif r < 0.91:
            sched.append((b"get", b"trk:%d" % rng.randrange(hot), None))
        else:
            sched.append((b"get", b"trk:%d" % rng.randrange(n_keys), None))
    return sched


async def _tracked_leg(tracked: bool, schedules: list, n_keys: int,
                       work_dir: str) -> tuple:
    """One in-process leg: a fresh single node on a real socket, K
    concurrent request-reply clients driving their schedules — plain
    RESP2 clients (every GET is a server round-trip) or tracked RESP3
    `NearCacheClient`s (a quiet-key GET never leaves the process).
    In-process (unlike `_serve_leg`'s fork) because the headline metric
    is the SERVER-side read-op count, read straight off the node's
    `cmds_processed` gauge (bumped once per client command on both the
    per-command and planned paths): the storm's delta minus its write
    count IS the reads that reached the server.  Returns
    (wall, counters, canonical-export)."""
    from constdb_tpu.chaos.cluster import Client
    from constdb_tpu.client import NearCacheClient
    from constdb_tpu.resp.message import Err
    from constdb_tpu.server.io import start_node
    from constdb_tpu.server.node import Node

    node = Node(node_id=1)
    app = await start_node(node, host="127.0.0.1", port=0,
                           work_dir=work_dir)
    addr = app.advertised_addr
    direct = await Client().connect(addr)
    try:
        # seed every key: the cold tail reads DATA, and both legs start
        # from the same per-key write history (seed, then owner serials)
        for i in range(n_keys):
            await direct.cmd(b"set", b"trk:%d" % i, b"seed:%d" % i)
        if tracked:
            clients = [await NearCacheClient(addr).connect()
                       for _ in schedules]
        else:
            clients = [await Client().connect(addr) for _ in schedules]
        n_writes = sum(1 for s in schedules for op, _k, _v in s
                       if op == b"set")
        cmds0 = node.stats.cmds_processed

        async def drive(c, sched):
            for op, k, v in sched:
                if op == b"set":
                    r = await (c.set(k, v) if tracked
                               else c.cmd(b"set", k, v))
                else:
                    r = await (c.get(k) if tracked else c.cmd(b"get", k))
                if isinstance(r, Err):
                    raise AssertionError(f"leg reply error: {r.val!r}")

        t0 = time.perf_counter()
        await asyncio.gather(*(drive(c, s)
                               for c, s in zip(clients, schedules)))
        wall = time.perf_counter() - t0
        # snapshot BEFORE the zero-stale oracle's direct reads below —
        # those are measurement traffic, not workload
        server_read_ops = node.stats.cmds_processed - cmds0 - n_writes
        stale = 0
        if tracked:
            # quiesce past the coalescing window, then the zero-stale
            # oracle: every entry still resident in every near-cache
            # must equal a direct read from the server
            await asyncio.sleep(0.3)
            for c in clients:
                await asyncio.sleep(0)
                for k, v in list(c.cache.items()):
                    if await direct.cmd(b"get", k) != v:
                        stale += 1
        st = node.stats
        counters = {
            "server_read_ops": server_read_ops,
            "stale_entries": stale,
            "tracking_invalidations_sent": st.tracking_invalidations_sent,
            "tracking_pushes": st.tracking_pushes,
            "tracking_demotions": st.tracking_demotions,
            "near_cache_hits": sum(getattr(c, "hits", 0)
                                   for c in clients),
            "near_cache_misses": sum(getattr(c, "misses", 0)
                                     for c in clients),
            "near_cache_invalidations": sum(
                getattr(c, "invalidations", 0) for c in clients),
            "near_cache_flushes": sum(getattr(c, "flushes", 0)
                                      for c in clients),
        }
        canon = app.node.canonical()
        for c in clients:
            await c.close()
        return wall, counters, canon
    finally:
        await direct.close()
        await app.close()


def tracked_main(args) -> None:
    """`bench.py --mode tracked`: the client-assisted-caching legs
    (round 22).  K tracked RESP3 near-cache clients vs K plain clients
    on the SAME deterministic hot-key 90:10 storm; the claim is
    server-side — the tracked leg's reads that actually reach the
    server must be >= 5x fewer — certified by the zero-stale oracle
    (every resident near-cache entry equals a direct read at quiesce)
    and the timestamp-stripped canonical export matching across legs.
    Emits one JSON line (BENCH_r22.json) with the host fingerprint."""
    import tempfile

    n_ops = int(os.environ.get("CONSTDB_BENCH_TRACKED_OPS", 40_000))
    n_clients = int(os.environ.get("CONSTDB_BENCH_TRACKED_CLIENTS", 4))
    n_keys = int(os.environ.get("CONSTDB_BENCH_TRACKED_KEYS", 512))
    hot = int(os.environ.get("CONSTDB_BENCH_TRACKED_HOT", 16))
    reps = int(os.environ.get("CONSTDB_BENCH_TRACKED_REPS", 2))
    floor = float(os.environ.get("CONSTDB_BENCH_TRACKED_FLOOR", 5.0))

    ensure_native()
    per_ops = n_ops // n_clients
    total = per_ops * n_clients
    schedules = [tracked_workload(ci, n_clients, per_ops, n_keys, hot)
                 for ci in range(n_clients)]
    n_reads = sum(1 for s in schedules for op, _k, _v in s
                  if op == b"get")
    print(f"[bench] tracked: {total} ops ({n_reads} reads) over "
          f"{n_clients} clients, {n_keys} keys (hot {hot})",
          file=sys.stderr)

    best: dict = {"tracked": None, "plain": None}
    for rep in range(reps):
        for name, is_tracked in (("plain", False), ("tracked", True)):
            with tempfile.TemporaryDirectory() as td:
                leg = asyncio.run(_tracked_leg(is_tracked, schedules,
                                               n_keys, td))
            print(f"[bench] rep {rep + 1} {name}: {leg[0]:.3f}s = "
                  f"{total / leg[0]:,.0f} op/s, "
                  f"{leg[1]['server_read_ops']} server reads",
                  file=sys.stderr)
            if best[name] is None or leg[0] < best[name][0]:
                best[name] = leg

    plain, tracked = best["plain"], best["tracked"]
    reduction = plain[1]["server_read_ops"] / \
        max(1, tracked[1]["server_read_ops"])
    hits = tracked[1]["near_cache_hits"]
    hit_rate = hits / max(1, hits + tracked[1]["near_cache_misses"])
    export_ok = strip_canonical_times(plain[2]) == \
        strip_canonical_times(tracked[2])
    verified = (export_ok
                and tracked[1]["stale_entries"] == 0
                and tracked[1]["tracking_invalidations_sent"] > 0
                and tracked[1]["tracking_demotions"] == 0
                and reduction >= floor)
    print(f"[bench] tracked: {plain[1]['server_read_ops']} -> "
          f"{tracked[1]['server_read_ops']} server reads = "
          f"{reduction:.1f}x reduction (floor {floor}x), hit rate "
          f"{hit_rate:.3f}; export {'OK' if export_ok else 'MISMATCH'}, "
          f"{tracked[1]['stale_entries']} stale", file=sys.stderr)

    out = {
        "metric": "tracked_server_read_reduction",
        "value": round(reduction, 2),
        "unit": "x fewer server-side reads",
        "mode": "tracked",
        "host_note": "in-process legs (client+server share the box): "
                     "the op-count reduction is load-independent, the "
                     "op/s walls are not",
        "ops": total,
        "reads": n_reads,
        "clients": n_clients,
        "keys": n_keys,
        "hot_keys": hot,
        "plain": {"op_per_s": round(total / plain[0], 1),
                  "wall_s": round(plain[0], 3),
                  **plain[1]},
        "tracked": {"op_per_s": round(total / tracked[0], 1),
                    "wall_s": round(tracked[0], 3),
                    "near_cache_hit_rate": round(hit_rate, 3),
                    **tracked[1]},
        "export_ok": export_ok,
        "verified": verified,
        "host": host_fingerprint(),
    }
    print(json.dumps(out))
    if not verified:
        sys.exit(1)


def serve_aof_main(args) -> None:
    """`bench.py --mode serve --aof`: the durability legs — the SAME
    pipelined serve workload against AOF-off / everysec / always
    servers, interleaved best-of-N, visible-value exports verified
    identical across legs, so the fsync tax is measured, not guessed.
    The `always` leg's produced log is then REPLAYED through the real
    recovery path (persist/oplog.py) with the replayed export verified
    against the leg's, yielding recovery seconds per GB of log."""
    import shutil
    import tempfile

    n_ops = int(os.environ.get("CONSTDB_BENCH_AOF_OPS", 60_000))
    n_conns = int(os.environ.get("CONSTDB_BENCH_SERVE_CONNS", 4))
    pipeline = int(os.environ.get("CONSTDB_BENCH_SERVE_PIPELINE", 64))
    n_keys = int(os.environ.get("CONSTDB_BENCH_SERVE_KEYS", 2000))
    serve_batch = int(os.environ.get("CONSTDB_BENCH_SERVE_BATCH", 512))
    engine_kind = os.environ.get("CONSTDB_BENCH_SERVE_ENGINE", "cpu")
    reps = int(os.environ.get("CONSTDB_BENCH_AOF_REPS", 2))

    ensure_native()
    per_ops = n_ops // n_conns
    per_conn = [serve_workload(ci, per_ops, n_keys, pipeline)
                for ci in range(n_conns)]
    total = per_ops * n_conns
    print(f"[bench] aof workload: {total} ops over {n_conns} conns x "
          f"{pipeline}-deep pipelines", file=sys.stderr)

    policies = (None, "everysec", "always")
    best: dict = {p: None for p in policies}
    best_dir: dict = {p: "" for p in policies}
    root = tempfile.mkdtemp(prefix="constdb-aofbench-")
    try:
        for rep in range(reps):
            for pol in policies:
                aof_dir = os.path.join(root, f"{pol}-{rep}") if pol \
                    else ""
                leg = _serve_leg(serve_batch, engine_kind, per_conn,
                                 aof_policy=pol, aof_dir=aof_dir)
                tag = pol or "off"
                print(f"[bench] rep {rep + 1} aof={tag}: {leg[0]:.3f}s "
                      f"= {total / leg[0]:,.0f} req/s "
                      f"({leg[4]['aof_size_bytes']} log bytes, "
                      f"{leg[4]['aof_fsyncs']} fsyncs)", file=sys.stderr)
                if best[pol] is None or leg[0] < best[pol][0]:
                    best[pol] = leg
                    best_dir[pol] = aof_dir

        off = best[None]
        stripped_off = strip_canonical_times(off[3])
        legs_out = []
        verified = True
        for pol in policies:
            wall, rtts, hashes, canon, stats = best[pol]
            ok = hashes == off[2] and \
                strip_canonical_times(canon) == stripped_off
            verified = verified and ok
            lat_ms = np.asarray(rtts) * 1000.0
            legs_out.append({
                "aof": pol or "off",
                "rps": round(total / wall, 1),
                "wall_s": round(wall, 3),
                "vs_off": round(off[0] / wall, 3),
                "reply_p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
                "reply_p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
                "aof_size_bytes": stats["aof_size_bytes"],
                "aof_fsyncs": stats["aof_fsyncs"],
                "aof_encoded_batches": stats["aof_encoded_batches"],
                "replies_ok": hashes == off[2],
            })

        # recovery replay of the `always` leg's log, timed (the real
        # boot path: persist/oplog.py recover through the merge engine)
        from constdb_tpu.persist import oplog as OL
        from constdb_tpu.server.node import Node as _Node
        rec_dir = best_dir["always"]
        log_bytes = sum(
            os.path.getsize(os.path.join(rec_dir, f))
            for f in os.listdir(rec_dir) if f.endswith(".log"))
        t0 = time.perf_counter()
        rnode = _Node(node_id=1, alias="recover")
        info = OL.recover(rnode, rec_dir)
        rec_wall = time.perf_counter() - t0
        # GC-invariant oracle: replayed visible values == the leg's
        for _ in range(64):
            rnode.gc()
            if not rnode.ks.garbage:
                break
        recov_ok = {k: v for k, v in
                    strip_canonical_times(rnode.canonical()).items()
                    if v[1]} == \
            {k: v for k, v in
             strip_canonical_times(best["always"][3]).items() if v[1]}
        verified = verified and recov_ok
        rec_per_gb = rec_wall / max(log_bytes / 1e9, 1e-9)
        print(f"[bench] recovery: {info.frames + info.batch_frames} ops "
              f"from {log_bytes} log bytes in {rec_wall:.3f}s = "
              f"{rec_per_gb:,.1f} s/GB; replay "
              f"{'OK' if recov_ok else 'MISMATCH'}", file=sys.stderr)

        out = {
            "metric": "serve_aof_everysec_vs_off",
            "value": legs_out[1]["vs_off"],
            "unit": "ratio",
            "mode": "serve-aof",
            "ops": total,
            "conns": n_conns,
            "pipeline": pipeline,
            "legs": legs_out,
            "recovery_wall_s": round(rec_wall, 3),
            "recovery_log_bytes": log_bytes,
            "recovery_s_per_gb": round(rec_per_gb, 2),
            "recovery_ops": info.frames + info.batch_frames,
            "recovery_verified": recov_ok,
            "engine": engine_kind,
            "verified": verified,
            "host": host_fingerprint(),
        }
        print(json.dumps(out))
        if not verified:
            sys.exit(1)
    finally:
        shutil.rmtree(root, ignore_errors=True)


# ---------------------------------------------------------------------------
# --mode recover: fast restart (BENCH_r20).  Recovery s/GB legs over the
# SAME always-fsync log — serial per-record reference vs bulk merge rounds
# vs concurrent per-shard segment replay vs a checkpointed tail — with the
# never-crashed leg's visible values as the oracle and byte-identity
# (canonical + full-state digest) required between serial and bulk.
# ---------------------------------------------------------------------------


def _recover_leg(aof_dir: str, bulk: bool, reps: int):
    """Timed in-process boot replays of one log dir (the real
    persist/oplog.py recover path); returns the best-of-reps
    (wall, node, info) with GC drained for the visible-value oracle.

    The timed region runs with the pre-existing heap FROZEN out of the
    cyclic collector: a real boot replays into a near-empty process,
    but by the time this leg runs the bench process retains every
    earlier leg's oracle state, and collector passes triggered inside
    the replay would scan that unrelated heap — inflating whichever
    leg happens to allocate more and drowning the s/GB signal."""
    import gc

    from constdb_tpu.persist import oplog as OL
    from constdb_tpu.server.node import Node as _Node

    best = None
    for _ in range(reps):
        node = _Node(node_id=1, alias="recover")
        gc.collect()
        gc.freeze()
        try:
            t0 = time.perf_counter()
            info = OL.recover(node, aof_dir, bulk=bulk)
            wall = time.perf_counter() - t0
        finally:
            gc.unfreeze()
        if best is None or wall < best[0]:
            best = (wall, node, info)
    wall, node, info = best
    _gc_drain(node)
    return wall, node, info


def _recover_pair(aof_dir: str, reps: int):
    """Serial and bulk legs with INTERLEAVED reps (serial, bulk, serial,
    bulk, ...): burstable builder hosts throttle over a run, so timing
    all serial reps before all bulk reps hands whichever leg goes first
    the faster CPU state and skews the ratio.  Returns the two
    best-of-reps (wall, node, info) triples."""
    s = b = None
    for _ in range(reps):
        sw = _recover_leg(aof_dir, False, 1)
        bw = _recover_leg(aof_dir, True, 1)
        if s is None or sw[0] < s[0]:
            s = sw
        if b is None or bw[0] < b[0]:
            b = bw
    return s, b


def _alive_values(canon: dict) -> dict:
    """The GC-invariant recovery oracle projection (see serve_aof_main):
    visible values of live keys only."""
    return {k: v for k, v in strip_canonical_times(canon).items() if v[1]}


def _gc_drain(node) -> None:
    for _ in range(64):
        node.gc()
        if not node.ks.garbage:
            break


def _frame_log_build(aof_dir: str, n_ops: int, n_keys: int):
    """Drive the exact single-loop command path with an armed op log:
    every write mirrors per-frame (Node.replicate_cmd ->
    OpLog.append_local), the REC_FRAME-heavy log shape that
    interactive shallow-pipeline traffic produces — the log where the
    serial replay reference is genuinely one apply per record.
    Returns the live node (GC-drained) as the never-crashed
    reference."""
    import random

    from constdb_tpu.persist import oplog as OL
    from constdb_tpu.resp.message import Arr, Bulk
    from constdb_tpu.server.node import Node as _Node

    rng = random.Random(1307)
    node = _Node(node_id=1, alias="framelog")
    lg = OL.OpLog(aof_dir, fsync_policy="no", node=node)
    node.oplog = lg
    for i in range(n_ops):
        r = rng.random()
        k = b"%05d" % rng.randrange(n_keys)
        if r < 0.25:
            body = (b"set", b"r" + k, b"v%08d" % i)
        elif r < 0.50:
            body = (b"incr", b"c" + k, b"%d" % rng.randrange(1, 100))
        elif r < 0.75:
            body = (b"sadd", b"s" + k,
                    *(b"m%03d" % rng.randrange(256) for _ in range(8)))
        elif r < 0.97:
            fv = []
            for f in range(10):
                fv += [b"f%02d" % rng.randrange(32), b"v%07d%d" % (i, f)]
            body = (b"hset", b"h" + k, *fv)
        elif r < 0.995:
            body = (b"srem", b"s" + k, b"m%03d" % rng.randrange(256))
        else:
            body = (b"del", b"r" + k)   # -> delbytes, columnar-encodable
        node.execute(Arr([Bulk(b) for b in body]))
    lg.close()
    node.oplog = None
    _gc_drain(node)
    return node


def _sharded_restart(aof_dir: str, recover_shards: int):
    """One in-process sharded restart over an existing per-shard log:
    boots the 2-shard plane with CONSTDB_RECOVER_SHARDS pinned, reads
    the recovery gauges, exports the consolidated canonical, closes.
    Returns (recovery_wall_s, gauges, alive-values projection)."""
    import asyncio

    from constdb_tpu.server.io import start_node
    from constdb_tpu.server.node import Node as _Node

    async def main():
        node = _Node(node_id=1, alias="rec")
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=os.path.dirname(aof_dir),
                               serve_shards=2, aof=True, aof_fsync="no",
                               aof_dir=aof_dir)
        try:
            x = node.stats.extra
            wall = x["recovery_wall_s"]
            gauges = {"recovery_mode": x["recovery_mode"],
                      "recovery_shards": x["recovery_shards"]}
            canon = await node.serve_plane.canonical()
        finally:
            await app.close()
        return wall, gauges, _alive_values(canon)

    os.environ["CONSTDB_RECOVER_SHARDS"] = str(recover_shards)
    try:
        return asyncio.run(main())
    finally:
        os.environ.pop("CONSTDB_RECOVER_SHARDS", None)


def _checkpoint_cut(src_dir: str, dst_dir: str, tail_ops: int) -> int:
    """Copy a log dir, run ONE incremental-checkpoint cut on the copy
    (the rewrite machinery recover_main's checkpointed-tail leg
    restarts from), then write a small post-cut tail of NEW keys over
    the socket — the restart must replay exactly that tail, nothing
    before the cut.  Returns the post-cut tail bytes."""
    import asyncio
    import shutil

    from constdb_tpu.chaos.cluster import Client
    from constdb_tpu.resp.codec import encode_msg
    from constdb_tpu.resp.message import Arr, Bulk
    from constdb_tpu.server.io import start_node
    from constdb_tpu.server.node import Node as _Node

    shutil.copytree(src_dir, dst_dir)

    async def main():
        node = _Node(node_id=1, alias="ckpt")
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=os.path.dirname(dst_dir),
                               aof=True, aof_fsync="no", aof_dir=dst_dir)
        try:
            await node.oplog.rewrite(app)
            assert node.oplog.checkpoint_uuid > 0
            c = await Client().connect(app.advertised_addr)
            try:
                buf = bytearray()
                for i in range(tail_ops):
                    buf += encode_msg(Arr([Bulk(b"SET"),
                                           Bulk(b"rtail:%d" % i),
                                           Bulk(b"tv%d" % i)]))
                c.writer.write(bytes(buf))
                await c.writer.drain()
                got = 0
                while got < tail_ops:
                    if c.parser.next_msg() is not None:
                        got += 1
                        continue
                    data = await asyncio.wait_for(
                        c.reader.read(1 << 16), 10.0)
                    assert data, "EOF mid-tail"
                    c.parser.feed(data)
            finally:
                c.writer.close()
            return node.oplog.size_bytes() - node.oplog.base_size
        finally:
            await app.close()

    return asyncio.run(main())


def recover_main(args) -> None:
    """`bench.py --mode recover`: the fast-restart curve — an
    always-fsync serve leg produces the log (its visible values are the
    never-crashed reference), then recovery replays it {serial
    per-record, bulk merge rounds, bulk + concurrent shard segments,
    checkpointed tail}, each timed as s/GB.  Serial and bulk must land
    byte-identical (canonical + full-state digest); every leg's alive
    values must equal the reference's."""
    import shutil
    import tempfile

    from constdb_tpu.store.digest import full_state_digest

    n_ops = int(os.environ.get("CONSTDB_BENCH_RECOVER_OPS", 60_000))
    n_conns = int(os.environ.get("CONSTDB_BENCH_SERVE_CONNS", 4))
    pipeline = int(os.environ.get("CONSTDB_BENCH_SERVE_PIPELINE", 64))
    n_keys = int(os.environ.get("CONSTDB_BENCH_SERVE_KEYS", 2000))
    serve_batch = int(os.environ.get("CONSTDB_BENCH_SERVE_BATCH", 512))
    engine_kind = os.environ.get("CONSTDB_BENCH_SERVE_ENGINE", "cpu")
    reps = int(os.environ.get("CONSTDB_BENCH_RECOVER_REPS", 3))

    ensure_native()
    per_ops = n_ops // n_conns
    per_conn = [serve_workload(ci, per_ops, n_keys, pipeline)
                for ci in range(n_conns)]
    total = per_ops * n_conns
    print(f"[bench] recover workload: {total} ops over {n_conns} conns x "
          f"{pipeline}-deep pipelines", file=sys.stderr)

    root = tempfile.mkdtemp(prefix="constdb-recbench-")
    try:
        # -- datasets: one unsharded always-fsync log + one 2-shard log
        flat_dir = os.path.join(root, "flat")
        leg = _serve_leg(serve_batch, engine_kind, per_conn,
                         aof_policy="always", aof_dir=flat_dir)
        live_vis = _alive_values(leg[3])
        log_bytes = sum(os.path.getsize(os.path.join(flat_dir, f))
                        for f in os.listdir(flat_dir)
                        if f.endswith(".log"))
        shard_dir = os.path.join(root, "shards")
        sleg = _serve_leg(serve_batch, engine_kind, per_conn,
                          serve_shards=2, aof_policy="always",
                          aof_dir=shard_dir)
        shard_vis = _alive_values(sleg[3])
        shard_bytes = sum(os.path.getsize(os.path.join(shard_dir, f))
                          for f in os.listdir(shard_dir)
                          if f.endswith(".log"))
        gb = max(log_bytes / 1e9, 1e-9)

        # -- serial reference vs bulk merge rounds, byte-identity bar
        (s_wall, s_node, s_info), (b_wall, b_node, b_info) = \
            _recover_pair(flat_dir, reps)
        s_canon, b_canon = s_node.canonical(), b_node.canonical()
        byte_identical = s_canon == b_canon and \
            full_state_digest(s_node.ks) == full_state_digest(b_node.ks)
        vis_ok = _alive_values(b_canon) == live_vis and \
            _alive_values(s_canon) == live_vis
        speedup = s_wall / b_wall
        print(f"[bench] batch log serial: {s_wall:.3f}s = "
              f"{s_wall / gb:,.1f} s/GB; "
              f"bulk: {b_wall:.3f}s = {b_wall / gb:,.1f} s/GB "
              f"({b_info.merge_rounds} rounds) -> {speedup:.2f}x; "
              f"byte-identical {'OK' if byte_identical else 'MISMATCH'}, "
              f"oracle {'OK' if vis_ok else 'MISMATCH'}", file=sys.stderr)

        # -- frame-record log (interactive shallow-pipeline shape): the
        # serial reference is genuinely one apply per record here, the
        # path the tentpole's s/GB bar is measured against.  The live
        # frame node itself is the never-crashed reference, and serial,
        # bulk and reference must agree byte-for-byte
        frame_dir = os.path.join(root, "frames")
        f_node = _frame_log_build(frame_dir, total, n_keys)
        f_canon = f_node.canonical()
        f_digest = full_state_digest(f_node.ks)
        frame_bytes = sum(os.path.getsize(os.path.join(frame_dir, f))
                          for f in os.listdir(frame_dir)
                          if f.endswith(".log"))
        fgb = max(frame_bytes / 1e9, 1e-9)
        (fs_wall, fs_node, fs_info), (fb_wall, fb_node, fb_info) = \
            _recover_pair(frame_dir, reps)
        frame_identical = \
            fs_node.canonical() == f_canon and \
            fb_node.canonical() == f_canon and \
            full_state_digest(fs_node.ks) == f_digest and \
            full_state_digest(fb_node.ks) == f_digest
        frame_speedup = fs_wall / fb_wall
        print(f"[bench] frame log ({frame_bytes} B): serial per-record: "
              f"{fs_wall:.3f}s = {fs_wall / fgb:,.1f} s/GB; bulk: "
              f"{fb_wall:.3f}s = {fb_wall / fgb:,.1f} s/GB "
              f"({fb_info.merge_rounds} rounds) -> {frame_speedup:.2f}x; "
              f"byte-identical "
              f"{'OK' if frame_identical else 'MISMATCH'}",
              file=sys.stderr)

        # -- shard curve: serial merged stream vs auto per-segment tasks
        sgb = max(shard_bytes / 1e9, 1e-9)
        shard_curve = []
        shards_ok = True
        for knob in (1, 0):
            wall, gauges, vis = _sharded_restart(shard_dir, knob)
            ok = vis == shard_vis
            shards_ok = shards_ok and ok
            shard_curve.append({
                "recover_shards_knob": knob,
                "recovery_wall_s": wall,
                "s_per_gb": round(wall / sgb, 2),
                **gauges,
                "verified": ok,
            })
            print(f"[bench] sharded restart knob={knob}: {wall:.3f}s "
                  f"({gauges['recovery_mode']}, "
                  f"{gauges['recovery_shards']} replay tasks); oracle "
                  f"{'OK' if ok else 'MISMATCH'}", file=sys.stderr)

        # -- checkpointed tail: one cut + a small post-cut tail, then a
        # timed restart that must replay ONLY the tail
        ckpt_dir = os.path.join(root, "ckpt")
        tail_n = max(64, total // 100)
        tail_bytes = _checkpoint_cut(flat_dir, ckpt_dir, tail_n)
        c_wall, c_node, c_info = _recover_leg(ckpt_dir, True, reps)
        full_ops = s_info.frames + s_info.batch_frames
        ckpt_ops = c_info.frames + c_info.batch_frames
        c_vis = _alive_values(c_node.canonical())
        # the tail only ADDS new keys: pre-cut acked state must survive
        # the cut byte-for-byte, and replay must stop at the tail
        ckpt_ok = all(c_vis.get(k) == v for k, v in live_vis.items()) \
            and c_vis.get(b"rtail:0") is not None \
            and 0 < ckpt_ops < full_ops
        print(f"[bench] checkpointed tail: {c_wall:.3f}s "
              f"({ckpt_ops} tail ops from {tail_bytes} tail bytes vs "
              f"{full_ops} full-log ops); oracle "
              f"{'OK' if ckpt_ok else 'MISMATCH'}", file=sys.stderr)

        verified = byte_identical and vis_ok and frame_identical \
            and shards_ok and ckpt_ok
        out = {
            "metric": "recovery_bulk_speedup_vs_serial",
            "value": round(frame_speedup, 2),
            "unit": "ratio",
            "mode": "recover",
            "host_note": "burstable 1-core box: the concurrent shard "
                         "legs cannot show a parallel wall-clock win "
                         "(every replay task shares the core, as in "
                         "BENCH_r19) — the curve still exercises and "
                         "gauge-records the per-segment concurrency; "
                         "the serial-vs-bulk ratios are core-count "
                         "independent (same process, same core).  The "
                         "headline ratio is the frame-record log (the "
                         "interactive shallow-pipeline shape, where "
                         "the serial reference is one apply per "
                         "record); the REPLBATCH log ratio rides in "
                         "legs[] — its records are already columnar, "
                         "so serial replay there is per-record only "
                         "in engine calls, not in python ops",
            "ops": total,
            "log_bytes": log_bytes,
            "frame_log_bytes": frame_bytes,
            "legs": [
                {"leg": "frames-serial", "wall_s": round(fs_wall, 3),
                 "s_per_gb": round(fs_wall / fgb, 2),
                 "ops": fs_info.frames + fs_info.batch_frames},
                {"leg": "frames-bulk", "wall_s": round(fb_wall, 3),
                 "s_per_gb": round(fb_wall / fgb, 2),
                 "merge_rounds": fb_info.merge_rounds,
                 "speedup_vs_serial": round(frame_speedup, 2),
                 "byte_identical": frame_identical},
                {"leg": "batch-serial", "wall_s": round(s_wall, 3),
                 "s_per_gb": round(s_wall / gb, 2),
                 "ops": s_info.frames + s_info.batch_frames},
                {"leg": "batch-bulk", "wall_s": round(b_wall, 3),
                 "s_per_gb": round(b_wall / gb, 2),
                 "merge_rounds": b_info.merge_rounds,
                 "speedup_vs_serial": round(speedup, 2),
                 "byte_identical": byte_identical},
                {"leg": "checkpointed-tail", "wall_s": round(c_wall, 3),
                 "tail_bytes": tail_bytes, "tail_ops": ckpt_ops,
                 "full_log_ops": full_ops},
            ],
            "shard_curve": shard_curve,
            "shard_log_bytes": shard_bytes,
            "engine": engine_kind,
            "verified": verified,
            "host": host_fingerprint(),
        }
        print(json.dumps(out))
        if not verified:
            sys.exit(1)
    finally:
        shutil.rmtree(root, ignore_errors=True)


# ---------------------------------------------------------------------------
# --mode intake: the native intake plane (BENCH_r19).  Serve legs with the
# C intake stage ON vs OFF (CONSTDB_NATIVE_INTAKE) plus the full-fallback
# CONSTDB_NO_NATIVE=1 leg, interleaved best-of-N, reply-stream + stripped-
# export oracle across ALL legs; wire legs time the REPLBATCH blob codec
# hot loops (native/wire.cpp) against the pure pack/unpack with encoded-
# byte identity as the oracle.
# ---------------------------------------------------------------------------


def _intake_wire_legs(reps: int = 3) -> dict:
    """In-process REPLBATCH codec legs: group-encode + decode a real
    repl-log entry stream with the native blob pack/unpack pinned OFF
    (pure Python) and ON, byte-identical encoded payloads required.
    Decode verifies via a column digest of every decoded batch."""
    import hashlib

    import constdb_tpu.replica.wire as W
    from constdb_tpu.server.node import Node

    n_frames = int(os.environ.get("CONSTDB_BENCH_INTAKE_FRAMES", 60_000))
    run_len = int(os.environ.get("CONSTDB_BENCH_WIRE_BATCH", 512))

    # a real encodable entry stream: plannable writes only, driven
    # through a live node so the entries are genuine LogEntry rows
    from constdb_tpu.resp.message import Arr, Bulk
    rng = np.random.default_rng(19)
    node = Node(node_id=1, alias="bench")
    for i in range(n_frames):
        k = b"w%d" % int(rng.integers(0, 4096))
        r = rng.random()
        if r < 0.40:
            body = (b"set", k, b"v%d" % i)
        elif r < 0.60:
            body = (b"incr", k + b":c")
        elif r < 0.80:
            body = (b"sadd", b"s" + k, b"m%d" % int(rng.integers(0, 64)))
        else:
            body = (b"hset", b"h" + k, b"f%d" % int(rng.integers(0, 16)),
                    b"v%d" % i)
        node.execute(Arr([Bulk(b) for b in body]))
    entries = list(node.repl_log._entries)
    runs = [entries[i:i + run_len]
            for i in range(0, len(entries), run_len)]

    def batch_digest(wb) -> bytes:
        h = hashlib.sha256()
        b = wb.batch
        for key in b.keys:
            h.update(key)
        for col in (b.key_enc, b.key_ct, b.key_mt, b.key_dt,
                    b.cnt_ki, b.cnt_val, b.cnt_uuid,
                    b.el_ki, b.el_add_t):
            h.update(np.ascontiguousarray(col).tobytes())
        for m in b.el_member:
            h.update(m or b"\0")
        return h.digest()

    def one_leg(native: bool):
        # pin the codec tier for this leg: [None] forces the pure
        # pack/unpack, a cleared cache re-resolves the extension
        W._WIRE_NATIVE_CACHE[:] = []
        if not native:
            W._WIRE_NATIVE_CACHE.append(None)
        enc_t = dec_t = 0.0
        payloads = []
        t0 = time.perf_counter()
        for run in runs:
            payloads.append(W.build_wire_batch(run, 1))
        enc_t = time.perf_counter() - t0
        assert all(p is not None for p in payloads), \
            "encodable run demoted during the wire bench"
        sink = Node(node_id=2, alias="sink")
        digests = []
        t0 = time.perf_counter()
        for run, payload in zip(runs, payloads):
            wb = W.decode_wire_batch(payload, sink.ks, 1,
                                     run[0].prev_uuid)
            digests.append(wb)
        dec_t = time.perf_counter() - t0
        digests = [batch_digest(wb) for wb in digests]
        return enc_t, dec_t, payloads, digests

    best = {True: None, False: None}
    oracle_ok = True
    for _rep in range(reps):
        for native in (True, False):
            enc_t, dec_t, payloads, digests = one_leg(native)
            cur = best[native]
            if cur is None or enc_t + dec_t < cur[0] + cur[1]:
                best[native] = (enc_t, dec_t, payloads, digests)
    W._WIRE_NATIVE_CACHE[:] = []  # leave the product tiering untouched
    n_enc, n_dec, n_pl, n_dg = best[True]
    p_enc, p_dec, p_pl, p_dg = best[False]
    oracle_ok = n_pl == p_pl and n_dg == p_dg
    frames = len(entries)
    return {
        "frames": frames,
        "runs": len(runs),
        "wire_batch": run_len,
        "payload_bytes": sum(len(p) for p in n_pl),
        "native": {"encode_s": round(n_enc, 4),
                   "decode_s": round(n_dec, 4),
                   "encode_frames_per_sec": round(frames / n_enc, 1),
                   "decode_frames_per_sec": round(frames / n_dec, 1)},
        "pure": {"encode_s": round(p_enc, 4),
                 "decode_s": round(p_dec, 4),
                 "encode_frames_per_sec": round(frames / p_enc, 1),
                 "decode_frames_per_sec": round(frames / p_dec, 1)},
        "encode_speedup": round(p_enc / n_enc, 2),
        "decode_speedup": round(p_dec / n_dec, 2),
        "verified": oracle_ok,
    }


def _intake_stage_legs(per_conn: list, reps: int = 3) -> dict:
    """The intake STAGE in isolation: split + classify + flatten a
    pipelined byte stream into ready-to-plan commands, C scanner
    (intake_scan via native_drain) vs the pure feed/drain-to-Msg loop.
    No planners, no merges — this measures exactly the Python the
    tentpole evicts; the end-to-end serve legs show what remains after
    the (shared) merge machinery floor."""
    from constdb_tpu.resp.codec import make_parser

    chunks = [data for conn in per_conn for data, _n in conn]
    total = sum(n for conn in per_conn for _data, n in conn)

    def native_leg() -> float:
        parser = make_parser()
        got = 0
        t0 = time.perf_counter()
        for data in chunks:
            parser.feed(data)
            while (nat := parser.native_drain()) is not None:
                got += len(nat[0])
            got += len(parser.drain())  # boundary remainders
        wall = time.perf_counter() - t0
        assert got == total, (got, total)
        return wall

    def pure_leg() -> float:
        parser = make_parser()
        got = 0
        t0 = time.perf_counter()
        for data in chunks:
            parser.feed(data)
            got += len(parser.drain())
        wall = time.perf_counter() - t0
        assert got == total, (got, total)
        return wall

    n_wall = min(native_leg() for _ in range(reps))
    p_wall = min(pure_leg() for _ in range(reps))
    return {
        "msgs": total,
        "native_msgs_per_sec": round(total / n_wall, 1),
        "pure_msgs_per_sec": round(total / p_wall, 1),
        "speedup": round(p_wall / n_wall, 2),
    }


def intake_main(args) -> None:
    """`bench.py --mode intake`: the native intake plane end to end
    (BENCH_r19).  Serve legs over real sockets — C intake stage vs the
    pure-Python drain path vs the CONSTDB_NO_NATIVE=1 full fallback —
    interleaved best-of-N on the same deterministic workload, reply
    byte streams + visible-value exports compared across every leg;
    the native leg must show `native_intake_chunks > 0`, the others
    exactly 0.  Emits ONE JSON line."""
    n_ops = int(os.environ.get("CONSTDB_BENCH_SERVE_OPS", 200_000))
    n_conns = int(os.environ.get("CONSTDB_BENCH_SERVE_CONNS", 4))
    pipeline = int(os.environ.get("CONSTDB_BENCH_SERVE_PIPELINE", 64))
    n_keys = int(os.environ.get("CONSTDB_BENCH_SERVE_KEYS", 2000))
    serve_batch = int(os.environ.get("CONSTDB_BENCH_SERVE_BATCH", 512))
    engine_kind = os.environ.get("CONSTDB_BENCH_SERVE_ENGINE", "cpu")
    reps = int(os.environ.get("CONSTDB_BENCH_SERVE_REPS", 2))

    ensure_native()
    from constdb_tpu.utils import native_tables as NT
    ext = NT.load_ext()
    if ext is None or not hasattr(ext, "intake_scan"):
        print("[bench] native extension with intake_scan unavailable — "
              "cannot run the intake legs", file=sys.stderr)
        sys.exit(1)

    per_ops = n_ops // n_conns
    per_conn = [serve_workload(ci, per_ops, n_keys, pipeline)
                for ci in range(n_conns)]
    total = per_ops * n_conns
    print(f"[bench] intake workload: {total} ops over {n_conns} conns x "
          f"{pipeline}-deep pipelines", file=sys.stderr)

    # leg -> env deltas for the FORKED server (fork inherits os.environ)
    legs = {
        "native": {"CONSTDB_NATIVE_INTAKE": "1"},
        "pure": {"CONSTDB_NATIVE_INTAKE": "0"},
        "nonative": {"CONSTDB_NO_NATIVE": "1"},
    }
    best: dict = {name: None for name in legs}
    for rep in range(reps):
        for name, env in legs.items():
            saved = {k: os.environ.get(k) for k in
                     ("CONSTDB_NATIVE_INTAKE", "CONSTDB_NO_NATIVE")}
            try:
                for k, v in env.items():
                    os.environ[k] = v
                leg = _serve_leg(serve_batch, engine_kind, per_conn)
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            print(f"[bench] rep {rep + 1} {name}: {leg[0]:.3f}s = "
                  f"{total / leg[0]:,.0f} req/s "
                  f"({leg[4]['native_intake_chunks']} native chunks)",
                  file=sys.stderr)
            if best[name] is None or leg[0] < best[name][0]:
                best[name] = leg

    ref_hashes = best["native"][2]
    ref_canon = strip_canonical_times(best["native"][3])
    verified = True
    legs_out = {}
    for name, (wall, rtts, hashes, canon, stats) in best.items():
        lat = np.asarray(rtts) * 1000.0
        replies_ok = hashes == ref_hashes
        export_ok = strip_canonical_times(canon) == ref_canon
        engaged_ok = stats["native_intake_chunks"] > 0 \
            if name == "native" else stats["native_intake_chunks"] == 0
        verified = verified and replies_ok and export_ok and engaged_ok
        legs_out[name] = {
            "rps": round(total / wall, 1),
            "wall_s": round(wall, 3),
            "reply_p50_ms": round(float(np.percentile(lat, 50)), 3),
            "reply_p99_ms": round(float(np.percentile(lat, 99)), 3),
            "native_intake_chunks": stats["native_intake_chunks"],
            "native_intake_msgs": stats["native_intake_msgs"],
            "serve_msgs_coalesced": stats["serve_msgs_coalesced"],
            "replies_ok": replies_ok,
            "export_ok": export_ok,
        }
        print(f"[bench] {name}: {legs_out[name]['rps']:,.1f} req/s, "
              f"replies {'OK' if replies_ok else 'MISMATCH'}, export "
              f"{'OK' if export_ok else 'MISMATCH'}, intake gauge "
              f"{'OK' if engaged_ok else 'WRONG'}", file=sys.stderr)

    stage = _intake_stage_legs(per_conn)
    print(f"[bench] intake stage alone: {stage['speedup']}x vs pure "
          f"({stage['native_msgs_per_sec']:,.0f} msgs/s)",
          file=sys.stderr)

    wire = _intake_wire_legs()
    verified = verified and wire["verified"]
    print(f"[bench] wire codec: encode {wire['encode_speedup']}x / "
          f"decode {wire['decode_speedup']}x vs pure "
          f"({'OK' if wire['verified'] else 'MISMATCH'})",
          file=sys.stderr)

    native_rps = legs_out["native"]["rps"]
    pure_rps = legs_out["pure"]["rps"]
    out = {
        "metric": "native_intake_serve_requests_per_sec",
        "value": native_rps,
        "unit": "requests/sec",
        "mode": "intake",
        "ops": total,
        "conns": n_conns,
        "pipeline": pipeline,
        "serve_batch": serve_batch,
        "legs": legs_out,
        "vs_pure_intake": round(native_rps / pure_rps, 2),
        "vs_no_native": round(native_rps / legs_out["nonative"]["rps"],
                              2),
        "stage": stage,
        "wire": wire,
        "host_note": "burstable 1-core box: client and server share the "
                     "core, so the serve ratio understates the server-"
                     "side intake win; the merge machinery (shared by "
                     "both legs) is the serving floor here — `stage` "
                     "isolates the evicted intake Python and `wire` the "
                     "REPLBATCH codec; the ROADMAP 3-5x serve target "
                     "applies on a >=4-core box",
        "engine": engine_kind,
        "verified": verified,
        "host": host_fingerprint(),
    }
    print(json.dumps(out))
    if not verified:
        sys.exit(1)


async def _overload_drive(port: int, per_conn: list, tallies: list,
                          rtts: list) -> None:
    """Pipelined driver that CLASSIFIES replies: (ok, oom, other_err)
    per connection, with per-window reply latency sampled exactly like
    _serve_drive — the latency of the non-shed traffic is the livelock
    gauge (a wedged shedding path shows up here, not in the shed
    count)."""
    import asyncio

    from constdb_tpu.resp.codec import make_parser
    from constdb_tpu.resp.message import Err
    from constdb_tpu.server.overload import OOM_ERR

    async def one(chunks, tally, sink):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        parser = make_parser()
        clock = time.perf_counter
        # windows are driven synchronously per chunk (send, then read
        # that window's replies) so the tally maps 1:1 onto windows and
        # the server is never more than one window deep per connection —
        # the firehose pressure comes from value size, not queue depth
        try:
            for data, n in chunks:
                t0 = clock()
                writer.write(data)
                await writer.drain()
                seen = 0
                while seen < n:
                    m = parser.next_msg()
                    if m is not None:
                        seen += 1
                        if isinstance(m, Err):
                            if bytes(m.val) == OOM_ERR:
                                tally[1] += 1
                            else:
                                tally[2] += 1
                        else:
                            tally[0] += 1
                        continue
                    b = await asyncio.wait_for(reader.read(1 << 16), 30.0)
                    if not b:
                        raise ConnectionError("server EOF under overload")
                    parser.feed(b)
                sink.append(clock() - t0)
        finally:
            writer.close()

    tallies.extend([0, 0, 0] for _ in per_conn)
    sinks = [[] for _ in per_conn]
    await asyncio.gather(*(one(c, t, s) for c, t, s
                           in zip(per_conn, tallies, sinks)))
    for s in sinks:
        rtts.extend(s)


def serve_overload_main(args) -> None:
    """`bench.py --mode serve --overload`: the overload leg — a real
    socket server with CONSTDB_MAXMEMORY set well below the workload's
    footprint.  The node must SURVIVE the firehose: shed client data
    writes with the exact -OOM error, keep serving the non-shed
    traffic with bounded reply latency (no livelock), and keep its
    accounting gauges consistent.  Emits ONE JSON line with the shed
    rate, req/s over the whole mix, and reply-window p50/p99."""
    import asyncio

    n_ops = int(os.environ.get("CONSTDB_BENCH_OVL_OPS", 40_000))
    n_conns = int(os.environ.get("CONSTDB_BENCH_SERVE_CONNS", 2))
    pipeline = int(os.environ.get("CONSTDB_BENCH_SERVE_PIPELINE", 64))
    val_len = int(os.environ.get("CONSTDB_BENCH_OVL_VAL", 256))
    maxmem = int(os.environ.get("CONSTDB_BENCH_OVL_MAXMEM", 2 << 20))
    engine_kind = os.environ.get("CONSTDB_BENCH_SERVE_ENGINE", "cpu")

    ensure_native()
    from constdb_tpu.resp.codec import encode_msg
    from constdb_tpu.resp.message import Arr, Bulk

    per_ops = n_ops // n_conns
    footprint = n_ops * (val_len + 64)
    print(f"[bench] overload workload: {n_ops} SETs x {val_len}B "
          f"(~{footprint >> 20}MB footprint) vs maxmemory "
          f"{maxmem >> 20}MB", file=sys.stderr)
    per_conn = []
    for ci in range(n_conns):
        chunks = []
        for lo in range(0, per_ops, pipeline):
            n = min(pipeline, per_ops - lo)
            # unique keys: the footprint must really GROW past the cap
            # (a cycling key set converges to its working-set size)
            chunks.append((b"".join(
                encode_msg(Arr([Bulk(b"set"),
                                Bulk(b"ovl:%d:%d" % (ci, lo + j)),
                                Bulk(b"v" * val_len)]))
                for j in range(n)), n))
        per_conn.append(chunks)

    # the forked server child inherits the env: the governor reads the
    # cap at Node construction
    os.environ["CONSTDB_MAXMEMORY"] = str(maxmem)
    try:
        import multiprocessing as mp
        ctx = mp.get_context("fork")
        parent, child = ctx.Pipe()
        p = ctx.Process(target=_serve_bench_server,
                        args=(child, 512, engine_kind, 1), daemon=True)
        p.start()
        child.close()
        try:
            port = parent.recv()
            if isinstance(port, BaseException):
                raise port
            tallies: list = []
            rtts: list = []
            t0 = time.perf_counter()
            asyncio.run(_overload_drive(port, per_conn, tallies, rtts))
            wall = time.perf_counter() - t0
            parent.send("stop")
            result = parent.recv()
            p.join()
            parent.close()
            if isinstance(result, BaseException):
                raise result
        except BaseException:
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
            raise
    finally:
        os.environ.pop("CONSTDB_MAXMEMORY", None)

    _canon, stats = result
    ok = sum(t[0] for t in tallies)
    oom = sum(t[1] for t in tallies)
    other = sum(t[2] for t in tallies)
    total = ok + oom + other
    lat_ms = np.asarray(rtts) * 1000.0
    p50, p99 = (float(np.percentile(lat_ms, q)) for q in (50, 99))
    survived = total == n_ops and other == 0
    gauges_ok = stats["oom_shed_writes"] == oom and oom > 0 and ok > 0 \
        and stats["used_memory"] >= maxmem * 0.5
    print(f"[bench] overload: {ok} landed / {oom} shed / {other} other "
          f"errors of {total} ({oom / max(total, 1):.1%} shed rate), "
          f"{total / wall:,.0f} req/s, window p50 {p50:.2f}ms "
          f"p99 {p99:.2f}ms; server used_memory={stats['used_memory']} "
          f"state={stats['overload_state']} "
          f"reclaims={stats['oom_hard_reclaims']}", file=sys.stderr)
    out = {
        "metric": "serve_overload_shed_rate",
        "value": round(oom / max(total, 1), 4),
        "unit": "fraction",
        "mode": "serve-overload",
        "ops": total,
        "landed": ok,
        "shed": oom,
        "other_errors": other,
        "rps": round(total / wall, 1),
        "reply_p50_ms": round(p50, 3),
        "reply_p99_ms": round(p99, 3),
        "maxmemory": maxmem,
        "used_memory": stats["used_memory"],
        "overload_state": stats["overload_state"],
        "oom_hard_reclaims": stats["oom_hard_reclaims"],
        "survived": bool(survived),
        "verified": bool(survived and gauges_ok),
        "host": host_fingerprint(),
    }
    print(json.dumps(out))
    if not out["verified"]:
        sys.exit(1)


def serve_shards_main(args) -> None:
    """`bench.py --mode serve --serve-shards 1,2[,4...]`: the
    shard-per-core SCALING CURVE — the same deterministic pipelined
    workload over real sockets against a server running each shard
    count (server/serve_shards.py), oracle-compared against the
    shards=1 leg (per-connection reply streams must be byte-identical,
    visible-value exports equal).  Emits ONE JSON line with req/s per
    shard count, per-shard serving stats, and the host fingerprint —
    plus an explicit host note when this box has too few cores for the
    curve to mean anything (client + router + workers > cores)."""
    n_ops = int(os.environ.get("CONSTDB_BENCH_SERVE_OPS", 200_000))
    n_conns = int(os.environ.get("CONSTDB_BENCH_SERVE_CONNS", 4))
    pipeline = int(os.environ.get("CONSTDB_BENCH_SERVE_PIPELINE", 64))
    n_keys = int(os.environ.get("CONSTDB_BENCH_SERVE_KEYS", 2000))
    serve_batch = int(os.environ.get("CONSTDB_BENCH_SERVE_BATCH", 512))
    engine_kind = os.environ.get("CONSTDB_BENCH_SERVE_ENGINE", "cpu")
    reps = int(os.environ.get("CONSTDB_BENCH_SERVE_REPS", 2))

    counts = sorted({max(1, int(s))
                     for s in str(args.serve_shards).split(",") if s})
    if 1 not in counts:
        counts = [1] + counts  # the oracle + scaling baseline

    ensure_native()
    per_ops = n_ops // n_conns
    total = per_ops * n_conns
    t0 = time.perf_counter()
    per_conn = [serve_workload(ci, per_ops, n_keys, pipeline)
                for ci in range(n_conns)]
    print(f"[bench] serve-shards workload: {total} ops over {n_conns} "
          f"conns x {pipeline}-deep pipelines, shard counts {counts} "
          f"({time.perf_counter() - t0:.1f}s gen)", file=sys.stderr)

    best: dict = {}
    for rep in range(reps):
        for k in counts:
            leg = _serve_leg(serve_batch, engine_kind, per_conn,
                             serve_shards=k)
            print(f"[bench] rep {rep + 1} serve_shards={k}: "
                  f"{leg[0]:.3f}s = {total / leg[0]:,.0f} req/s",
                  file=sys.stderr)
            if k not in best or leg[0] < best[k][0]:
                best[k] = leg

    bwall, _rt, bhashes, bcanon, _bst = best[1]
    base_strip = strip_canonical_times(bcanon)
    curve = []
    verified = True
    for k in counts:
        wall, rtts, hashes, canon, stats = best[k]
        ok = hashes == bhashes and \
            strip_canonical_times(canon) == base_strip
        verified = verified and ok
        lat_ms = np.asarray(rtts) * 1000.0
        curve.append({
            "serve_shards": k,
            "rps": round(total / wall, 1),
            "wall_s": round(wall, 3),
            "speedup_vs_1": round(bwall / wall, 3),
            "reply_p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
            "reply_p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
            "verified_vs_shards1": ok,
            "serve_xshard_barriers": stats.get("serve_xshard_barriers", 0),
            "per_shard": stats.get("per_shard", {}),
        })
        print(f"[bench] serve_shards={k}: {total / wall:,.0f} req/s "
              f"({bwall / wall:.2f}x vs 1) "
              f"{'verified' if ok else 'MISMATCH'}", file=sys.stderr)

    ncpu = os.cpu_count() or 1
    host_note = ""
    if ncpu < max(counts) + 2:
        host_note = (
            f"this box has {ncpu} cores; a serve_shards={max(counts)} leg "
            f"needs ~{max(counts) + 2} (bench client + router + workers) "
            "to show scaling — the curve here measures capacity "
            "CONTENTION, not the architecture's ceiling.  The shards=1 "
            "path is the exact single-loop PR 5 serving path; the "
            "differential suite (tests/test_serve_shards.py) pins the "
            "multi-shard legs byte-identical, so the curve on a "
            ">=4-core box is the number that matters.")
        print(f"[bench] host note: {host_note}", file=sys.stderr)

    out = {
        "metric": "serve_shard_scaling",
        "value": curve[-1]["rps"],
        "unit": "requests/sec",
        "mode": "serve",
        "ops": total,
        "conns": n_conns,
        "pipeline": pipeline,
        "serve_batch": serve_batch,
        "serve_shards_curve": curve,
        "engine": engine_kind,
        "verified": verified,
        "host": host_fingerprint(),
        "host_note": host_note,
    }
    print(json.dumps(out))
    if not verified:
        sys.exit(1)


# --------------------------------------------------------------------------
# --mode resync: digest-driven delta resync vs full snapshot


class _ResyncSink:
    """StreamWriter stand-in for the REAL ReplicaLink push loop: parses
    the pusher's wire stream as it is written, answers digest questions
    from the puller store's matrix (bridged into the link's ack queue
    exactly the way the pull loop does), and collects the
    FULLSYNC/DELTASYNC payload for the timed apply.  Every byte is
    counted in both directions — `bytes_out` is the pusher's stream,
    `bytes_back` the encoded size the puller's acks would occupy."""

    def __init__(self, link, ks):
        from constdb_tpu.resp.codec import make_parser
        self.link = link
        self.ks = ks
        self.parser = make_parser()
        self.bytes_out = 0
        self.bytes_back = 0
        self.payload = bytearray()
        self.payload_kind = None
        self.repl_last = 0
        self.n_buckets = 0
        self.digest_frames = 0
        self.done = asyncio.Event()
        self.closed = False
        self._want = 0
        self._matrix = {}

    def write(self, data: bytes) -> None:
        self.bytes_out += len(data)
        self.parser.feed(bytes(data))
        self._pump()

    async def drain(self) -> None:
        await asyncio.sleep(0)

    def close(self) -> None:
        self.closed = True

    def _pump(self) -> None:
        from constdb_tpu.replica.link import DELTASYNC, DIGEST, FULLSYNC
        from constdb_tpu.resp.message import Arr, as_bytes, as_int
        while True:
            if self._want:
                raw = self.parser.take_raw(self._want)
                if not raw:
                    return
                self.payload += raw
                self._want -= len(raw)
                if self._want:
                    return
                self.done.set()
            msg = self.parser.next_msg()
            if msg is None:
                return
            items = msg.items if isinstance(msg, Arr) else None
            assert items, f"unexpected frame {msg!r}"
            kind = as_bytes(items[0]).lower()
            if kind == DIGEST:
                self.digest_frames += 1
                self._answer(items)
            elif kind in (FULLSYNC, DELTASYNC):
                self.payload_kind = kind
                self._want = as_int(items[1])
                self.repl_last = as_int(items[2])
                if kind == DELTASYNC and len(items) > 3:
                    self.n_buckets = as_int(items[3])
            # PARTSYNC / REPLICATE / REPLACK heartbeats: not part of the
            # resync transfer under measurement

    def _answer(self, items) -> None:
        from constdb_tpu.replica.link import DIGESTACK
        from constdb_tpu.resp.codec import encode_msg
        from constdb_tpu.resp.message import Arr, Bulk, Int, as_bytes, as_int
        from constdb_tpu.store.digest import state_digest_matrix
        token, level = as_int(items[1]), as_int(items[2])
        fanout, leaves = as_int(items[3]), as_int(items[4])
        key = (token, fanout, leaves)
        mat = self._matrix.get(key)
        if mat is None:
            # the puller-side fold runs inside the timed span — it is
            # real resync CPU cost on the receiving node
            mat = state_digest_matrix(self.ks, fanout, leaves)
            self._matrix = {key: mat}
        if level == 0:
            theirs = np.frombuffer(as_bytes(items[5]), dtype="<u8")
            mine = mat.sum(axis=1, dtype=np.uint64)
            reply = np.nonzero(mine != theirs)[0].astype("<i8").tobytes()
        elif level == 2:
            from constdb_tpu.store.digest import stamp_mismatch_indices
            crcs = np.frombuffer(as_bytes(items[5]),
                                 dtype="<u4").astype(np.uint64)
            stamps = np.frombuffer(as_bytes(items[6]), dtype="<u8")
            reply = stamp_mismatch_indices(
                self.ks, crcs, stamps).astype("<i4").tobytes()
        else:
            shards = np.frombuffer(as_bytes(items[5]),
                                   dtype="<i8").astype(np.int64)
            sub = np.frombuffer(as_bytes(items[6]),
                                dtype="<u8").reshape(len(shards), leaves)
            srow, leaf = np.nonzero(mat[shards] != sub)
            reply = (shards[srow] * leaves + leaf).astype("<i8").tobytes()
        ack = [Bulk(DIGESTACK), Int(token), Int(level), Bulk(reply)]
        self.bytes_back += len(encode_msg(Arr(ack)))
        self.link._digest_acks.put_nowait(ack)


class _ResyncDump:
    """shared_dump stand-in producing a REAL full snapshot of the node's
    current state on acquire — the dump cost lands inside the full-sync
    leg's wall, exactly where a cold shared dump pays it."""

    def __init__(self, node, work_dir: str):
        self.node = node
        self.work_dir = work_dir

    async def acquire(self, compressed=False):
        from constdb_tpu.persist.share import Dump
        from constdb_tpu.persist.snapshot import NodeMeta, dump_keyspace
        self.node.ensure_flushed()
        path = os.path.join(self.work_dir, "resync_full.snapshot")
        size = dump_keyspace(path, self.node.ks,
                             NodeMeta(node_id=self.node.node_id),
                             container_level=6 if compressed else 0)
        return Dump(path=path, repl_last=self.node.repl_log.last_uuid,
                    size=size)


def _resync_engine(kind: str):
    if kind == "cpu":
        return CpuMergeEngine()
    from constdb_tpu.engine.tpu import TpuMergeEngine
    return TpuMergeEngine()


def _resync_divergence(ks: KeySpace, kids: np.ndarray, uuid: int,
                       tag: bytes) -> ColumnarBatch:
    """LWW register overwrites of `kids` at `uuid` as ONE state batch
    (the divergent writes a partitioned pusher accumulated)."""
    sel = np.asarray(kids, dtype=_I64)
    idx = sel.tolist()
    n = len(idx)
    b = ColumnarBatch()
    b.rows_unique_per_slot = True
    b.keys = [ks.key_bytes[i] for i in idx]
    b.key_enc = np.ascontiguousarray(ks.keys.enc[sel])
    b.key_ct = np.ascontiguousarray(ks.keys.ct[sel])
    b.key_mt = np.full(n, uuid, dtype=_I64)
    b.key_dt = np.ascontiguousarray(ks.keys.dt[sel])
    b.key_expire = np.ascontiguousarray(ks.keys.expire[sel])
    b.reg_val = [tag] * n
    b.reg_t = np.full(n, uuid, dtype=_I64)
    b.reg_node = np.full(n, 9, dtype=_I64)
    return b


async def _resync_leg(node, app, puller_ks, puller_engine, delta: bool,
                      timeout: float = 900.0):
    """One measured resync: drive the REAL push loop against an off-ring
    peer (resume=0) whose capabilities do/don't include CAP_DELTA_SYNC,
    stream into the sink, then merge the payload into the puller store.
    Wall covers negotiate + stream + apply + flush.  Returns
    (wall_s, sink, stats_delta_dict)."""
    from constdb_tpu.persist.snapshot import SectionDemux
    from constdb_tpu.replica.link import (CAP_DELTA_SYNC,
                                          CAP_FULLSYNC_RESET, ReplicaLink)
    from constdb_tpu.replica.manager import ReplicaMeta
    import io as _io
    st = node.stats
    before = (st.repl_delta_syncs, st.repl_full_syncs,
              st.repl_digest_rounds, st.repl_delta_bytes,
              st.extra.get("repl_delta_demotions", 0))
    link = ReplicaLink(app, ReplicaMeta(addr="bench:0"))
    link._peer_caps = CAP_FULLSYNC_RESET | (CAP_DELTA_SYNC if delta else 0)
    link._digest_acks = asyncio.Queue()
    sink = _ResyncSink(link, puller_ks)
    t0 = time.perf_counter()
    task = asyncio.create_task(link._push_loop(sink, peer_resume=0))
    done_wait = asyncio.create_task(sink.done.wait())
    try:
        # watch the push loop TOO: an exception inside it would leave
        # sink.done unset forever — surface it now instead of burning
        # the whole timeout and failing the oracle with no root cause
        finished, _ = await asyncio.wait(
            {task, done_wait}, timeout=timeout,
            return_when=asyncio.FIRST_COMPLETED)
        if not finished:
            raise TimeoutError(f"resync leg incomplete after {timeout}s")
        if not sink.done.is_set():
            task.result()  # raises the push loop's actual error
            raise RuntimeError("push loop exited without syncing")
    finally:
        for t in (task, done_wait):
            t.cancel()
        try:
            await task
        except (asyncio.CancelledError, Exception):
            pass
    for chunk in SectionDemux(_io.BytesIO(bytes(sink.payload))).batches():
        puller_engine.merge(puller_ks, chunk)
    if getattr(puller_engine, "needs_flush", False):
        puller_engine.flush(puller_ks)
    wall = time.perf_counter() - t0
    return wall, sink, {
        "delta_syncs": st.repl_delta_syncs - before[0],
        "full_syncs": st.repl_full_syncs - before[1],
        "digest_rounds": st.repl_digest_rounds - before[2],
        "delta_bytes": st.repl_delta_bytes - before[3],
        "demotions": st.extra.get("repl_delta_demotions", 0) - before[4],
    }


def resync_main(args) -> None:
    """`bench.py --mode resync`: anti-entropy resync cost at small
    divergence — a converged 2-store pair diverges a configurable key
    fraction past the pusher's repl_log ring, then both resync legs run
    through the REAL ReplicaLink push loop: digest-negotiated delta
    (CAP_DELTA_SYNC peer) vs full snapshot (legacy peer), same pusher
    state, bytes-on-wire and wall measured for each.  Oracle: both
    pullers' canonical exports must equal the pusher's on a
    deterministic subsample (plus a full-state digest cross-check).
    Emits ONE JSON line (BENCH_r11) with the per-fraction curve."""
    import tempfile
    import types as _types
    from constdb_tpu.store.digest import (DIGEST_FANOUT, leaves_for,
                                          state_digest_matrix)
    from constdb_tpu.resp.message import Bulk
    from constdb_tpu.server.node import Node

    n_keys = int(os.environ.get("CONSTDB_BENCH_RESYNC_KEYS", 1_000_000))
    n_rep = int(os.environ.get("CONSTDB_BENCH_RESYNC_REPLICAS", 2))
    fracs = sorted(float(f) for f in os.environ.get(
        "CONSTDB_BENCH_RESYNC_FRACS", "0.001,0.01,0.1").split(",") if f)
    engine_kind = os.environ.get("CONSTDB_BENCH_RESYNC_ENGINE", "tpu")
    verify_target = int(os.environ.get("CONSTDB_BENCH_RESYNC_VERIFY",
                                       100_000))
    chunk = int(os.environ.get("CONSTDB_BENCH_CHUNK", 1 << 17))

    ensure_native()
    t0 = time.perf_counter()
    batches = make_workload(n_keys, n_rep)
    chunks = chunk_batches(batches, chunk)
    n_cnt = int(n_keys * 0.4)
    n_reg = int(n_keys * 0.3)
    if int(fracs[-1] * n_keys) > n_reg:
        raise SystemExit(f"max fraction {fracs[-1]} exceeds the register "
                         f"key range ({n_reg}/{n_keys})")

    # pusher node + two puller stores, all converged on the same state
    pusher = Node(node_id=1, engine=_resync_engine(engine_kind))
    for c in chunks:
        pusher.engine.merge(pusher.ks, c)
    pusher.ensure_flushed()
    pullers = {}
    for name in ("delta", "full"):
        eng = _resync_engine(engine_kind)
        ks = KeySpace()
        for c in chunks:
            eng.merge(ks, c)
        if getattr(eng, "needs_flush", False):
            eng.flush(ks)
        pullers[name] = (ks, eng)
    print(f"[bench] resync pair: {n_keys} keys x {n_rep} replicas built "
          f"({time.perf_counter() - t0:.1f}s gen+merge, engine="
          f"{engine_kind})", file=sys.stderr)

    workdir = tempfile.mkdtemp(prefix="constdb-resync-")
    app = _types.SimpleNamespace(
        node=pusher, heartbeat=0.05, reconnect_delay=0.05,
        handshake_timeout=60.0, work_dir=workdir, delta_sync=True,
        advertised_addr="bench:0")
    app.shared_dump = _ResyncDump(pusher, workdir)
    pusher.repl_log.cap = 16  # any divergence burst falls off this ring

    sample = subsample_keys(batches[0].keys, n_keys, verify_target)
    leaves = leaves_for(n_keys, DIGEST_FANOUT,
                        getattr(app, "delta_bucket_keys", 8))
    total_buckets = DIGEST_FANOUT * leaves

    async def run() -> tuple[list, bool]:
        curve = []
        all_ok = True
        for epoch, frac in enumerate(fracs, start=1):
            n_div = max(1, int(frac * n_keys))
            kids = np.arange(n_cnt, n_cnt + n_div, dtype=_I64)
            uuid = (MS0 + 1_000_000 + epoch * 1000) << SEQ_BITS
            div = _resync_divergence(pusher.ks, kids, uuid,
                                     b"E%d" % epoch)
            pusher.engine.merge(pusher.ks, div)
            pusher.ensure_flushed()
            pusher.hlc.observe(uuid)
            # two real logged writes on a 16-byte ring: the first evicts,
            # so every peer resume below it is off-ring (the resync
            # trigger), while the survivor keeps repl_last coherent
            for i in range(2):
                wu = pusher.hlc.tick(True)
                wkey = b"__resync_ring_%d_%d" % (epoch, i)
                kid, _ = pusher.ks.get_or_create(wkey, S.ENC_BYTES, wu)
                pusher.ks.register_set(kid, b"r", wu, pusher.node_id)
                pusher.ks.touch("env", "reg")
                pusher.repl_log.push(wu, b"set", [Bulk(wkey), Bulk(b"r")])
            assert not pusher.repl_log.can_resume_from(0)

            row = {"frac": frac, "n_div": n_div}
            for name, is_delta in (("delta", True), ("full", False)):
                ks, eng = pullers[name]
                wall, sink, st = await _resync_leg(
                    pusher, app, ks, eng, delta=is_delta)
                wire = sink.bytes_out + sink.bytes_back
                row[f"{name}_wall_s"] = round(wall, 3)
                row[f"{name}_bytes"] = wire
                if is_delta:
                    row["delta_payload_kind"] = \
                        sink.payload_kind.decode()
                    row["digest_rounds"] = st["digest_rounds"]
                    row["digest_frame_bytes"] = wire - len(sink.payload)
                    row["buckets_streamed"] = sink.n_buckets
                    row["demoted"] = st["demotions"] > 0
                print(f"[bench] frac={frac} {name}: {wire:,} bytes, "
                      f"{wall:.3f}s"
                      + (f" ({sink.n_buckets}/{total_buckets} buckets, "
                         f"{st['digest_rounds']} digest rounds)"
                         if is_delta else ""), file=sys.stderr)
            row["bytes_ratio"] = round(row["delta_bytes"]
                                       / row["full_bytes"], 4)
            row["speedup"] = round(row["full_wall_s"]
                                   / max(row["delta_wall_s"], 1e-9), 2)

            # oracle: both pullers converged to the pusher, on an
            # independent canonical subsample + the digest matrix,
            # whose mod-2^64 fold is exactly the chaos oracle's scalar
            # digest (store/digest.py full_state_digest) — derived from
            # the already-computed matrices, not a second keyspace scan
            want = pusher.ks.canonical(keys=sample)
            wmat = state_digest_matrix(pusher.ks, DIGEST_FANOUT, leaves)
            wsum = int(wmat.sum(dtype=np.uint64))
            ok = True
            for name, (ks, _eng) in pullers.items():
                got = ks.canonical(keys=sample)
                pmat = state_digest_matrix(ks, DIGEST_FANOUT, leaves)
                dok = bool((pmat == wmat).all()) and \
                    int(pmat.sum(dtype=np.uint64)) == wsum
                cok = compare_canonical(got, want) == 0
                ok = ok and dok and cok
                print(f"[bench] frac={frac} verify {name}: canonical "
                      f"{'OK' if cok else 'MISMATCH'} ({len(sample)} "
                      f"keys), digest {'OK' if dok else 'MISMATCH'}",
                      file=sys.stderr)
            row["verified"] = ok
            all_ok = all_ok and ok
            curve.append(row)
        return curve, all_ok

    curve, verified = asyncio.run(run())
    # headline: bytes ratio at the largest fraction <= 1% divergence
    # (the ISSUE acceptance bar: <= 0.10 of the full-snapshot bytes)
    small = [r for r in curve if r["frac"] <= 0.01] or curve[:1]
    out = {
        "metric": "resync_delta_bytes_ratio",
        "value": small[-1]["bytes_ratio"],
        "unit": "delta_bytes/full_bytes",
        "mode": "resync",
        "keys": n_keys,
        "replicas": n_rep,
        "engine": engine_kind,
        "digest_fanout": DIGEST_FANOUT,
        "digest_leaves": leaves,
        "curve": curve,
        "verified": verified,
        "host": host_fingerprint(),
    }
    print(json.dumps(out))
    if not verified:
        sys.exit(1)


def snapshot_resident_legs(args, chunks, batches, n_keys, n_rep, group,
                           fold, oracle, verify_on, cpu_rate, note) -> None:
    """`--resident 0,1` snapshot legs: interleaved best-of-2 catch-up
    merges of the SAME chunk stream through a device-resident engine
    (state persists across chunk merges, one flush at the end) vs the
    non-resident engine (per-round state upload + download), both
    oracle-verified, with per-leg transfer counters (BENCH_r12).
    Single-keyspace path only (the process pool pins resident=True)."""
    from constdb_tpu.engine.tpu import TpuMergeEngine
    from constdb_tpu.store.sharded_keyspace import ShardedKeySpace

    legs = [int(x) for x in str(args.resident).split(",")]
    stores = {}
    walls = {r: float("inf") for r in legs}
    for _ in range(2):
        for r in legs:
            sks = stores.get(r)
            if sks is None:
                sks = stores[r] = ShardedKeySpace(
                    n_shards=1, group=group,
                    engine_factory=lambda rr=r: TpuMergeEngine(
                        resident=bool(rr), dense_fold=fold))
            sks.reset()
            t0 = time.perf_counter()
            for c in chunks:
                sks.submit(c)
            sks.flush()
            walls[r] = min(walls[r], time.perf_counter() - t0)
    want = None
    oracle_err = None
    if verify_on and oracle is not None:
        try:
            oracle[1].send("go")
        except OSError as e:
            oracle_err = str(e) or type(e).__name__
    curve = []
    verified = None
    sub_keys = subsample_keys(batches[0].keys, n_keys) if verify_on else None
    if verify_on and oracle is not None and oracle_err is None:
        p, rx = oracle
        try:
            want = rx.recv()
        except (EOFError, OSError) as e:
            want = None
            oracle_err = str(e) or type(e).__name__
        finally:
            p.join()
    for r in legs:
        sks = stores[r]
        secs = sks.host_secs_per_shard()[0]
        leg = {"resident": r, "wall_s": round(walls[r], 3),
               "keys_per_sec": round(n_keys / walls[r], 1),
               "dev_upload_bytes": secs.get("bytes_h2d", 0),
               "dev_download_bytes": secs.get("bytes_d2h", 0),
               "dev_rounds_resident": secs.get("dev_rounds_resident", 0),
               "host_micro_rounds": secs.get("host_micro_rounds", 0),
               "flush_rows_downloaded":
                   secs.get("flush_rows_downloaded", 0),
               "flush_rows_full_equiv":
                   secs.get("flush_rows_full_equiv", 0),
               "folds": secs.get("folds", 0)}
        if want is not None and not isinstance(want, Exception):
            diffs = compare_canonical(sks.canonical(keys=sub_keys), want)
            leg["diffs"] = diffs
            verified = (verified is not False) and diffs == 0
        curve.append(leg)
        print(f"[bench] resident={r}: {walls[r]:.3f}s = "
              f"{leg['keys_per_sec']:,.0f} keys/s; h2d "
              f"{leg['dev_upload_bytes']:,} d2h "
              f"{leg['dev_download_bytes']:,}"
              + (f" ({leg['diffs']} diffs)" if "diffs" in leg else ""),
              file=sys.stderr)
        sks.close() if hasattr(sks, "close") else None
    out = {
        "metric": "snapshot_merge_keys_per_sec",
        "value": curve[-1]["keys_per_sec"],
        "unit": "keys/sec",
        "mode": "snapshot",
        "keys": n_keys,
        "replicas": n_rep,
        "vs_baseline": round(curve[-1]["keys_per_sec"] / cpu_rate, 2),
        "resident_curve": curve,
        "verified": verified,
        "host": host_fingerprint(),
    }
    if oracle_err is not None:
        out["verify_error"] = oracle_err
    if note:
        out["note"] = note
    print(json.dumps(out))
    if verified is False:
        sys.exit(1)


def cluster_workload_ops(conn_id: int, n_ops: int, n_keys: int,
                         seed: int = 13) -> list:
    """serve_workload's exact command mix, one entry per op as
    (routing_key, encoded_bytes): the cluster legs partition the SAME
    op stream by slot owner, so every leg applies the identical total
    workload and the union of per-group visible-value exports must
    equal the single group's (the cross-leg oracle).  Keys stay
    conn-prefixed (single writer per key), and a key's ops never change
    group within a leg, so per-key histories are leg-invariant."""
    import random

    from constdb_tpu.resp.codec import encode_into
    from constdb_tpu.resp.message import Arr, Bulk

    rng = random.Random(seed * 1000 + conn_id)
    pfx = b"c%d:" % conn_id
    ops = []
    for i in range(n_ops):
        r = rng.random()
        k = pfx + b"%05d" % rng.randrange(n_keys)
        if r < 0.25:
            body = (b"set", b"r" + k, b"v%08d" % i)
        elif r < 0.50:
            body = (b"incr", b"c" + k, b"%d" % rng.randrange(1, 100))
        elif r < 0.75:
            body = (b"sadd", b"s" + k,
                    *(b"m%03d" % rng.randrange(256) for _ in range(8)))
        elif r < 0.95:
            fv = []
            for f in range(10):
                fv += [b"f%02d" % rng.randrange(32), b"v%07d%d" % (i, f)]
            body = (b"hset", b"h" + k, *fv)
        elif r < 0.97:
            body = (b"get", b"r" + k)
        elif r < 0.995:
            body = (b"srem", b"s" + k, b"m%03d" % rng.randrange(256))
        else:
            body = (b"del", b"r" + k)
        buf = bytearray()
        encode_into(buf, Arr([Bulk(b) for b in body]))
        ops.append((body[1], bytes(buf)))
    return ops


def _partition_cluster_ops(ops_per_conn: list, n_groups: int,
                           pipeline: int) -> list:
    """Route each op to its slot's owner under even_split(n_groups) and
    chunk into pipeline windows: per-group, per-connection pre-encoded
    chunks in _serve_drive's (bytes, n) shape.  Relative op order per
    connection is preserved inside each group, so same-key ops (always
    the same group) keep their history order."""
    from constdb_tpu.cluster import even_split, slot_of

    owner = even_split(n_groups).owner
    groups = []
    for g in range(n_groups):
        per_conn = []
        for ops in ops_per_conn:
            chunks, cur, n = [], bytearray(), 0
            for key, data in ops:
                if owner[slot_of(key)] != g:
                    continue
                cur += data
                n += 1
                if n >= pipeline:
                    chunks.append((bytes(cur), n))
                    cur = bytearray()
                    n = 0
            if n:
                chunks.append((bytes(cur), n))
            if chunks:
                per_conn.append(chunks)
        groups.append(per_conn)
    return groups


def _cluster_bench_server(pipe, serve_batch: int, engine_kind: str,
                          n_groups: int, gid: int,
                          enabled: bool = True) -> None:
    """Forked cluster-group server: _serve_bench_server's GC posture
    and pipe protocol (port up, block until stop, ship back canonical +
    stats), with the slot router enabled at `n_groups` groups.
    enabled=False forks the exact pre-cluster node — the
    redirect-overhead baseline leg."""
    import asyncio
    import gc

    from constdb_tpu.server.io import start_node
    from constdb_tpu.server.node import Node

    gc.collect()
    gc.freeze()
    gc.set_threshold(100_000, 50, 50)

    def make_engine():
        if engine_kind == "cpu":
            from constdb_tpu.engine.cpu import CpuMergeEngine
            return CpuMergeEngine()
        from constdb_tpu.conf import build_engine
        return build_engine(engine_kind)

    async def main():
        node = Node(node_id=1 + gid, alias=f"bench-g{gid}",
                    engine=make_engine())
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir="/tmp", serve_batch=serve_batch,
                               serve_shards=1, cluster=enabled,
                               slot_groups=n_groups, cluster_group=gid)
        pipe.send(app.port)
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, pipe.recv)  # block until "stop"
        node.ensure_flushed()
        cl = node.cluster
        pipe.send((node.canonical(), {
            "cmds_processed": node.stats.cmds_processed,
            "serve_msgs_coalesced": node.stats.serve_msgs_coalesced,
            "redirects_sent": cl.redirects_sent if cl is not None else 0,
            "epoch": cl.epoch if cl is not None else 0,
            "slots_owned": cl.table.slots_owned(gid)
            if cl is not None else 0,
        }))
        await app.close()

    try:
        asyncio.run(main())
    except BaseException as e:  # parent surfaces the failure
        try:
            pipe.send(e)
        except OSError:
            pass
    finally:
        pipe.close()


def _cluster_leg(serve_batch: int, engine_kind: str, n_groups: int,
                 per_group_conns: list, enabled: bool = True):
    """One cluster leg: fork one server per group, drive every group's
    connections concurrently in a single loop (fully pipelined), return
    (wall_s, reply_hashes, canonicals, stats).  Wall is the envelope
    over all groups — the cluster's throughput clock."""
    import asyncio
    import multiprocessing as mp

    ctx = mp.get_context("fork")
    procs, parents, ports = [], [], []
    try:
        for g in range(n_groups):
            parent, child = ctx.Pipe()
            p = ctx.Process(target=_cluster_bench_server,
                            args=(child, serve_batch, engine_kind,
                                  n_groups, g, enabled),
                            daemon=True)
            p.start()
            child.close()
            procs.append(p)
            parents.append(parent)
        for parent in parents:
            port = parent.recv()
            if isinstance(port, BaseException):
                raise port
            ports.append(port)
        rtts: list = []
        hashes: list = []

        async def drive_all():
            await asyncio.gather(*(
                _serve_drive(ports[g], per_group_conns[g], rtts, hashes)
                for g in range(n_groups) if per_group_conns[g]))

        t0 = time.perf_counter()
        asyncio.run(drive_all())
        wall = time.perf_counter() - t0
        canons, stats = [], []
        for parent in parents:
            parent.send("stop")
            result = parent.recv()
            if isinstance(result, BaseException):
                raise result
            canons.append(result[0])
            stats.append(result[1])
        for p in procs:
            p.join()
        for parent in parents:
            parent.close()
        return wall, hashes, canons, stats
    except BaseException:
        for p in procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
        raise


def _cluster_migrate_leg(mig_keys: int, mig_slots: int) -> dict:
    """In-process two-group live migration: load group-0 keys, migrate
    slots [0, mig_slots) to group 1, and measure wall + shipped payload
    bytes against (a) the migrated range's own encoded size per round
    and (b) the FULL state's encoded size — the O(slot bytes) evidence:
    a slot move costs the slot's bytes times the round count, not the
    keyspace's."""
    import asyncio

    import numpy as np

    from constdb_tpu.cluster import (NSLOTS, SLOT_FANOUT, SLOT_LEAVES,
                                     bucket_of_slot, slot_of)
    from constdb_tpu.cluster.migrate import migrate_slot_range
    from constdb_tpu.engine.cpu import CpuMergeEngine
    from constdb_tpu.persist.snapshot import _encode_batch
    from constdb_tpu.resp.message import Bulk, Err
    from constdb_tpu.server.commands import execute
    from constdb_tpu.server.io import start_node
    from constdb_tpu.server.node import Node
    from constdb_tpu.store.digest import export_bucket_batch

    async def run() -> dict:
        node0 = Node(node_id=1, alias="mig-src", engine=CpuMergeEngine())
        node1 = Node(node_id=2, alias="mig-dst", engine=CpuMergeEngine())
        app0 = await start_node(node0, host="127.0.0.1", port=0,
                                work_dir="/tmp", cluster=True,
                                slot_groups=2, cluster_group=0)
        app1 = await start_node(node1, host="127.0.0.1", port=0,
                                work_dir="/tmp", cluster=True,
                                slot_groups=2, cluster_group=1)
        try:
            # group-0 state: every key this leg writes is owned by gid 0
            # (even_split(2): slots [0, 8192)); keys in the migrated
            # range double as the post-flip serving probes
            moved_probe = None
            written = 0
            i = 0
            while written < mig_keys:
                key = b"mig:%07d" % i
                i += 1
                s = slot_of(key)
                if s >= NSLOTS // 2:
                    continue
                r = execute(node0, [Bulk(b"set"), Bulk(key),
                                    Bulk(b"v%062d" % i)])
                assert not isinstance(r, Err), r
                written += 1
                if moved_probe is None and s < mig_slots:
                    moved_probe = key
            node0.ensure_flushed()
            full_bytes = len(bytes(_encode_batch(export_bucket_batch(
                node0.ks, SLOT_FANOUT, SLOT_LEAVES,
                np.ones(NSLOTS, dtype=bool)))))
            mask = np.zeros(NSLOTS, dtype=bool)
            for s in range(mig_slots):
                mask[bucket_of_slot(s)] = True
            range_bytes = len(bytes(_encode_batch(export_bucket_batch(
                node0.ks, SLOT_FANOUT, SLOT_LEAVES, mask))))

            t0 = time.perf_counter()
            res = await migrate_slot_range(node0, app0, 0, mig_slots,
                                           app1.advertised_addr)
            wall = time.perf_counter() - t0

            cl0, cl1 = node0.cluster, node1.cluster
            probe_on_target = execute(node1, [Bulk(b"get"),
                                              Bulk(moved_probe)])
            probe_on_source = execute(node0, [Bulk(b"get"),
                                              Bulk(moved_probe)])
            rounds_per_slot = res["rounds"] / max(1, res["slots"])
            ok = (res["slots"] == mig_slots
                  and cl0.epoch == cl1.epoch == 1 + mig_slots
                  and cl0.migrations_out == mig_slots
                  and cl1.migrations_in == mig_slots
                  and not cl0.migrating and not cl1.importing
                  and cl0.gc_pin() is None and cl1.gc_pin() is None
                  and not isinstance(probe_on_target, Err)
                  and isinstance(probe_on_source, Err)
                  and probe_on_source.val.startswith(b"MOVED ")
                  # O(slot bytes): shipped ~= range bytes x rounds, and
                  # the range is a small fraction of the full state
                  and res["bytes"] <= range_bytes * rounds_per_slot * 1.5
                  and range_bytes < full_bytes / 4)
            return {
                "ok": ok,
                "slots": res["slots"],
                "rounds": res["rounds"],
                "wall_s": round(wall, 3),
                "slots_per_sec": round(res["slots"] / wall, 1),
                "shipped_bytes": res["bytes"],
                "range_state_bytes": range_bytes,
                "full_state_bytes": full_bytes,
                "shipped_vs_full": round(res["bytes"] / full_bytes, 4),
                "keys": written,
                "epoch": cl0.epoch,
            }
        finally:
            await app0.close()
            await app1.close()

    return asyncio.run(run())


def cluster_main(args) -> None:
    """`bench.py --mode cluster`: the hash-slot partitioning legs
    (BENCH_r21.json).

    SCALING — one deterministic op stream partitioned by slot owner,
    driven against 1 group vs N groups concurrently; the union of the
    per-group visible-value exports must equal the single group's (no
    key lost or duplicated across the partition), and every leg must
    finish with zero redirects (client partitioning and server routing
    agree on the slot math).  REDIRECT TAX — cluster-on at one group
    (router engaged on every command, every slot owned) vs the exact
    pre-cluster node, interleaved best-of-N with reply-hash + export
    oracle; the slot check must cost <= ~2%.  MIGRATION — a live
    slot-range migration between two in-process groups: wall, shipped
    bytes vs the range's and the full state's encoded bytes (the
    O(slot bytes) evidence), moved keys serving from the target."""
    n_ops = int(os.environ.get("CONSTDB_BENCH_CLUSTER_OPS", 120_000))
    n_conns = int(os.environ.get("CONSTDB_BENCH_CLUSTER_CONNS", 4))
    pipeline = int(os.environ.get("CONSTDB_BENCH_CLUSTER_PIPELINE", 64))
    n_keys = int(os.environ.get("CONSTDB_BENCH_CLUSTER_KEYS", 2000))
    n_groups = int(os.environ.get("CONSTDB_BENCH_CLUSTER_GROUPS", 4))
    serve_batch = int(os.environ.get("CONSTDB_BENCH_SERVE_BATCH", 512))
    engine_kind = os.environ.get("CONSTDB_BENCH_CLUSTER_ENGINE", "cpu")
    reps = int(os.environ.get("CONSTDB_BENCH_CLUSTER_REPS", 3))
    mig_keys = int(os.environ.get("CONSTDB_BENCH_CLUSTER_MIG_KEYS", 20_000))
    mig_slots = int(os.environ.get("CONSTDB_BENCH_CLUSTER_MIG_SLOTS", 128))

    ensure_native()
    per_ops = n_ops // n_conns
    total = per_ops * n_conns
    t0 = time.perf_counter()
    ops_per_conn = [cluster_workload_ops(ci, per_ops, n_keys)
                    for ci in range(n_conns)]
    parts = {g: _partition_cluster_ops(ops_per_conn, g, pipeline)
             for g in {1, n_groups}}
    print(f"[bench] cluster workload: {total} ops over {n_conns} conns x "
          f"{pipeline}-deep pipelines, {n_groups} groups "
          f"({time.perf_counter() - t0:.1f}s gen)", file=sys.stderr)

    # interleaved best-of-N: off (pre-cluster node), on (router engaged,
    # one group), grp (the n_groups partition)
    best: dict = {}

    def run_leg(rep: int, tag: str, g: int, enabled: bool) -> None:
        leg = _cluster_leg(serve_batch, engine_kind, g, parts[g], enabled)
        print(f"[bench] rep {rep} {tag} (groups={g} "
              f"cluster={'on' if enabled else 'off'}): "
              f"{leg[0]:.3f}s = {total / leg[0]:,.0f} req/s",
              file=sys.stderr)
        if tag not in best or leg[0] < best[tag][0]:
            best[tag] = leg

    for rep in range(reps):
        for tag, g, enabled in (("off", 1, False), ("on", 1, True),
                                ("grp", n_groups, True)):
            run_leg(rep + 1, tag, g, enabled)
    # extra interleaved off/on pairs: the tax target (~2%) is far below
    # a burstable box's rep-to-rep swing, so the pair needs more
    # best-of samples than the scaling curve does
    tax_reps = int(os.environ.get("CONSTDB_BENCH_CLUSTER_TAX_REPS", 3))
    for rep in range(tax_reps):
        for tag, g, enabled in (("off", 1, False), ("on", 1, True)):
            run_leg(reps + rep + 1, tag, g, enabled)
    wall_off, hashes_off, canons_off, _ = best["off"]
    wall_on, hashes_on, canons_on, stats_on = best["on"]
    wall_grp, _hashes_grp, canons_grp, stats_grp = best["grp"]
    rps_off, rps_on, rps_grp = (total / w
                                for w in (wall_off, wall_on, wall_grp))
    overhead_pct = (wall_on - wall_off) / wall_off * 100.0
    scaling = rps_grp / rps_on

    # the noise-free tax estimate: the per-command work cluster mode
    # adds to the serve path is exactly one cl.route(key) on an owned
    # slot (commands.py) — time it in-process and express it as a
    # fraction of the measured per-op budget
    from constdb_tpu.cluster import ClusterState, even_split
    rcl = ClusterState(0, even_split(1))
    sample = [k for k, _ in ops_per_conn[0][:2000]]
    route_iters = 50
    t0 = time.perf_counter()
    for _ in range(route_iters):
        for k in sample:
            rcl.route(k)
    route_ns = ((time.perf_counter() - t0)
                / (route_iters * len(sample)) * 1e9)
    route_pct = route_ns * rps_on / 1e7  # ns/op x op/s -> % of budget

    # oracle 1: the redirect-tax pair is the SAME workload on the same
    # connection schedule — reply streams and exports must match exactly
    replies_ok = hashes_on == hashes_off
    tax_export_ok = (strip_canonical_times(canons_on[0])
                     == strip_canonical_times(canons_off[0]))
    # oracle 2: the partition is lossless — per-group exports are
    # disjoint and their union is the single group's export
    grp_strips = [strip_canonical_times(c) for c in canons_grp]
    union: dict = {}
    disjoint = True
    for s in grp_strips:
        disjoint = disjoint and not (union.keys() & s.keys())
        union.update(s)
    union_ok = disjoint and union == strip_canonical_times(canons_on[0])
    # oracle 3: client partitioning agreed with server routing — the
    # router ran on every command yet never redirected
    redirects_ok = (stats_on[0]["redirects_sent"] == 0
                    and all(s["redirects_sent"] == 0 for s in stats_grp))

    print(f"[bench] migration leg: {mig_keys} keys, "
          f"slots [0, {mig_slots})", file=sys.stderr)
    mig = _cluster_migrate_leg(mig_keys, mig_slots)

    verified = (replies_ok and tax_export_ok and union_ok
                and redirects_ok and mig["ok"])
    print(f"[bench] {n_groups} groups: {rps_grp:,.0f} req/s vs 1 group "
          f"{rps_on:,.0f} req/s = {scaling:.2f}x; redirect tax "
          f"{overhead_pct:+.2f}% e2e best-of-{reps + tax_reps}, "
          f"{route_ns:.0f}ns/route = {route_pct:.2f}% of the per-op "
          f"budget (target <= 2%); migration "
          f"{mig['slots']} slots in {mig['wall_s']}s, "
          f"{mig['shipped_bytes']} B shipped = "
          f"{mig['shipped_vs_full']:.2%} of full state", file=sys.stderr)
    print(f"[bench] verify: replies {'OK' if replies_ok else 'MISMATCH'}, "
          f"tax export {'OK' if tax_export_ok else 'MISMATCH'}, "
          f"partition union {'OK' if union_ok else 'MISMATCH'} "
          f"({len(union)} keys), redirects "
          f"{'OK' if redirects_ok else 'NONZERO'}, migration "
          f"{'OK' if mig['ok'] else 'FAILED'}", file=sys.stderr)

    ncpu = os.cpu_count() or 1
    host_note = ""
    if ncpu < n_groups + 2:
        host_note = (
            f"this box has {ncpu} cores; a {n_groups}-group scaling leg "
            f"needs ~{n_groups + 2} (bench client + one core per group) "
            "to show scaling — every group server shares the core here, "
            "so the ratio measures capacity CONTENTION, not the "
            "architecture's ceiling.  The partition itself is pinned "
            "lossless by the union-canonical oracle and the zero-"
            "redirect check (plus tests/test_cluster.py), so the "
            ">=2.5x number applies on a >=4-core box.  The e2e "
            "redirect-tax number is CPU-credit noise-dominated here "
            "(identical legs swing +/-15% rep-to-rep, as in BENCH_r18) "
            "— route_check_pct_of_op is the core-count-independent "
            "measurement of the added per-command work.")
        print(f"[bench] host note: {host_note}", file=sys.stderr)

    out = {
        "metric": "cluster_group_scaling",
        "value": round(scaling, 2),
        "unit": "ratio",
        "mode": "cluster",
        "groups": n_groups,
        "ops": total,
        "conns": n_conns,
        "pipeline": pipeline,
        "serve_batch": serve_batch,
        "rps_1group": round(rps_on, 1),
        "rps_ngroup": round(rps_grp, 1),
        "rps_cluster_off": round(rps_off, 1),
        "redirect_overhead_pct": round(overhead_pct, 2),
        "route_check_ns": round(route_ns, 1),
        "route_check_pct_of_op": round(route_pct, 3),
        "redirect_target_pct": 2.0,
        "slots_owned": [s["slots_owned"] for s in stats_grp],
        "group_cmds": [s["cmds_processed"] for s in stats_grp],
        "migration": mig,
        "engine": engine_kind,
        "verified": verified,
        "host": host_fingerprint(),
        "host_note": host_note,
    }
    print(json.dumps(out))
    if not verified:
        sys.exit(1)


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description="constdb-tpu snapshot-merge "
                                 "benchmark")
    ap.add_argument("--shards", type=int, default=None,
                    help="hash-shard the host merge across this many "
                    "worker processes (default: CONSTDB_SHARDS / auto; "
                    "1 = single-keyspace path)")
    ap.add_argument("--mode",
                    choices=["snapshot", "stream", "serve", "resync",
                             "tensor", "intake", "recover", "cluster",
                             "tracked"],
                    default="snapshot",
                    help="snapshot = bulk catch-up merge (default); "
                    "stream = steady-state replication apply through the "
                    "coalescing pull path; serve = pipelined client "
                    "serving over real sockets through the serve "
                    "coalescer; resync = digest-negotiated delta resync "
                    "vs full snapshot at configurable divergence; "
                    "tensor = resident device tensor-register merges + "
                    "reads vs the host reference at micro-batch size; "
                    "intake = the native intake plane — C intake stage "
                    "vs pure-Python serve legs + the REPLBATCH codec "
                    "legs (BENCH_r19); recover = fast-restart s/GB "
                    "curve — serial vs bulk merge rounds vs concurrent "
                    "shard segments vs checkpointed tail (BENCH_r20); "
                    "cluster = hash-slot partitioning — group-scaling "
                    "vs 1 group with a union-canonical oracle, the "
                    "redirect-check tax vs the pre-cluster node, and a "
                    "live slot-range migration's O(slot bytes) cost "
                    "(BENCH_r21); tracked = client-assisted caching — "
                    "K tracked near-cache clients vs K plain clients "
                    "on a hot-key 90:10 storm, server-side read-op "
                    "reduction with a zero-stale + stripped-export "
                    "oracle (BENCH_r22)")
    ap.add_argument("--frame-log", default=None,
                    help="stream mode: record the generated frame log "
                    "here (or replay it if the file exists)")
    ap.add_argument("--wire", action="store_true",
                    help="stream mode: run the socket-to-socket WIRE "
                    "legs instead of the in-process apply replay — "
                    "batch wire (REPLBATCH) vs per-frame wire vs the "
                    "intra-node baseline, plus a 3-node mesh "
                    "differential (BENCH_r14)")
    ap.add_argument("--resident", default=None,
                    help="snapshot/stream modes: comma list of 0|1 legs "
                    "(e.g. 0,1) — interleaves device-resident vs "
                    "host-path engine legs and records per-leg transfer "
                    "counters (BENCH_r12)")
    ap.add_argument("--serve-shards", default=None,
                    help="serve mode: comma list of shard counts (e.g. "
                    "1,2) — runs the shard-per-core scaling curve "
                    "instead of the coalesced-vs-per-command comparison")
    ap.add_argument("--aof", action="store_true",
                    help="serve mode: the DURABILITY legs — AOF off / "
                    "everysec / always interleaved on the same workload "
                    "(fsync tax), plus a timed recovery replay of the "
                    "always leg's log (s/GB) — BENCH_r17")
    ap.add_argument("--overload", action="store_true",
                    help="serve mode: the OVERLOAD leg — maxmemory set "
                    "below the workload's footprint; reports shed rate, "
                    "survival, and non-shed reply latency "
                    "(server/overload.py)")
    ap.add_argument("--read-pct", default=None,
                    help="serve mode: read-heavy legs at these read "
                    "percentages (e.g. '90,50') — coalesced+cache vs "
                    "cache-off vs the per-command baseline, "
                    "reply-hash + stripped-export oracle across all "
                    "legs (BENCH_r18.json)")
    ap.add_argument("--peers", type=int, default=0,
                    help="stream mode: the broadcast FAN-OUT legs — one "
                    "pusher driving 1..N real push loops, encode-once "
                    "cache on vs off interleaved, every peer "
                    "oracle-verified, plus the compressed-vs-plain "
                    "bulk-sync bytes leg (BENCH_r16)")
    args, _ = ap.parse_known_args()
    if args.mode == "stream":
        if args.peers:
            fanout_main(args)
        elif args.wire:
            wire_main(args)
        else:
            stream_main(args)
        return
    if args.mode == "serve":
        if args.aof:
            serve_aof_main(args)
        elif args.overload:
            serve_overload_main(args)
        elif args.serve_shards:
            serve_shards_main(args)
        elif args.read_pct:
            serve_read_main(args)
        else:
            serve_main(args)
        return
    if args.mode == "intake":
        intake_main(args)
        return
    if args.mode == "recover":
        recover_main(args)
        return
    if args.mode == "cluster":
        cluster_main(args)
        return
    if args.mode == "tracked":
        tracked_main(args)
        return
    if args.mode == "resync":
        resync_main(args)
        return
    if args.mode == "tensor":
        tensor_main(args)
        return
    # default = the BASELINE.json north-star scale (10M keys x 8 replicas);
    # the CPU baseline rate is measured on a capped key count (the per-row
    # engine's keys/sec is scale-flat, the 10M run would take ~20 min)
    n_keys = int(os.environ.get("CONSTDB_BENCH_KEYS", 10_000_000))
    n_rep = int(os.environ.get("CONSTDB_BENCH_REPLICAS", 8))
    n_cpu = min(n_keys, int(os.environ.get("CONSTDB_BENCH_CPU_KEYS",
                                           min(n_keys, 200_000))))
    chunk = int(os.environ.get("CONSTDB_BENCH_CHUNK", 1 << 17))

    print(f"[bench] workload: {n_keys} keys x {n_rep} replicas, "
          f"{chunk}-key chunks (cpu baseline on {n_cpu} keys)",
          file=sys.stderr)

    # native tables first: BOTH engines (and the oracle) resolve keys
    # through them, and the pure-Python fallback tiers dominated the
    # round-5 host dispatch profile
    ensure_native()

    t0 = time.perf_counter()
    cpu_chunks = chunk_batches(make_workload(n_cpu, n_rep, seed=7), chunk)
    cpu_t, _ = time_engine(CpuMergeEngine, cpu_chunks, repeats=1)
    cpu_rate = n_cpu / cpu_t
    print(f"[bench] cpu engine: {cpu_t:.3f}s on {n_cpu} keys "
          f"= {cpu_rate:,.0f} keys/s (workload gen+run "
          f"{time.perf_counter() - t0:.1f}s)", file=sys.stderr)

    # Workload gen BEFORE any in-process jax init: the verify oracle forks
    # HERE (forking a JAX-threaded process is unsafe) and then idles until
    # the timed runs complete.
    t0 = time.perf_counter()
    batches = make_workload(n_keys, n_rep, seed=7)
    print(f"[bench] workload gen: {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)
    verify_on = os.environ.get("CONSTDB_BENCH_VERIFY", "1") != "0"
    oracle = start_oracle(batches, n_keys) if verify_on else None

    # Probe the device backend OUT-OF-PROCESS before touching jax here: a
    # wedged tunnel-attached device hangs in-process init forever (round-1
    # BENCH_r01.json died on exactly this).  On a bad probe we still print
    # a valid JSON line from the XLA-on-CPU device path so the driver
    # always records a number.
    from constdb_tpu.utils.backend import force_cpu_platform, probe_backend

    probe = probe_backend()
    note = ""
    if not probe.ok:
        note = f"device backend unavailable ({probe.error}); XLA-on-CPU fallback"
        print(f"[bench] WARNING: {note}", file=sys.stderr)
        force_cpu_platform()

    # bench context: plenty of host RAM is provisioned, so let the win
    # pool cover the whole run — one flush, minimum link round-trips
    # (servers keep the conservative default; see engine pool_flush_bytes)
    os.environ.setdefault("CONSTDB_POOL_FLUSH_MB", "8192")
    from constdb_tpu.engine.tpu import TpuMergeEngine
    import jax
    # persistent compile cache: state shapes recur across runs (pow2-padded),
    # so repeated bench invocations skip the ~0.7 s/kernel XLA compiles.
    # NEVER under a forced interpret backend: an interpret-mode pallas_call
    # lowers through per-process python callbacks, and a cache-reloaded
    # executable resolves a STALE callback id — the kernel silently runs
    # the wrong python body and corrupts merge output (caught by the
    # resident smoke's oracle: rep 1 verified, rep 2 garbage)
    try:
        if "interpret" not in os.environ.get("CONSTDB_BENCH_FOLD", "auto"):
            jax.config.update("jax_compilation_cache_dir",
                              os.environ.get("CONSTDB_JAX_CACHE",
                                             "/tmp/constdb_jax_cache"))
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.1)
    except Exception:
        pass
    print(f"[bench] jax backend: {jax.default_backend()} "
          f"devices={jax.devices()}", file=sys.stderr)

    t0 = time.perf_counter()
    chunks = chunk_batches(batches, chunk)
    print(f"[bench] chunking: {time.perf_counter() - t0:.1f}s "
          f"({len(chunks)} chunks)", file=sys.stderr)
    # default to the grouped shape: the engine's hierarchical host combine
    # folds each aligned replica-cluster and concatenates the disjoint
    # folds, so a group spanning several key ranges still collapses to ONE
    # device call per family — the same cadence the replica link uses in
    # production (link.py apply_group)
    group = int(os.environ.get("CONSTDB_BENCH_GROUP", str(4 * n_rep)))
    fold = os.environ.get("CONSTDB_BENCH_FOLD", "auto")
    from constdb_tpu.store.sharded_keyspace import (ShardedKeySpace,
                                                    default_shards)
    if args.resident is not None:
        snapshot_resident_legs(args, chunks, batches, n_keys, n_rep, group,
                               fold, oracle, verify_on, cpu_rate, note)
        return
    shards = args.shards if args.shards is not None else default_shards()
    # every run goes through the sharded keyspace facade: shards == 1 is
    # the degenerate single-keyspace path (byte-identical to driving the
    # engine directly — tests/test_sharded_keyspace.py pins it) so the
    # JSON line always carries per-shard host_secs; shards > 1 fans the
    # same chunk stream out by key hash to worker processes (one
    # KeySpace + resident engine each), so cnt/el staging and flush
    # apply run on all cores instead of one
    if shards > 1:
        # job granularity: one replica-aligned cluster per job (n_rep
        # chunks of one key range) keeps the worker-side fold intact
        # while giving the parent-encode → worker-merge pipeline several
        # jobs in flight; the single-path `group` would put the whole
        # stream in ~2 jobs and serialize encode against merge
        sgroup = int(os.environ.get("CONSTDB_SHARD_GROUP", str(n_rep)))
        print(f"[bench] sharded merge: {shards} worker processes, "
              f"{sgroup}-chunk jobs", file=sys.stderr)
        # carry the fold knob into the worker processes (captured into
        # the pool env at creation); CONSTDB_SHARD_ENGINE is honored here
        # exactly as on the replica-ingest path (README Tuning table)
        os.environ.setdefault("CONSTDB_SHARD_FOLD", fold)
        sks = ShardedKeySpace(
            n_shards=shards, mode="process",
            engine_spec=os.environ.get("CONSTDB_SHARD_ENGINE", "tpu"),
            group=sgroup)
    else:
        sks = ShardedKeySpace(
            n_shards=1, group=group,
            engine_factory=lambda: TpuMergeEngine(resident=True,
                                                  dense_fold=fold))
    # best-of-2 even at the 10M scale: the driver records a single bench
    # invocation, and one unlucky run (shared box, tunnel variance)
    # should not be the round's number (~90s extra, well within budget)
    tpu_t = float("inf")
    for _ in range(2):
        sks.reset()
        t0 = time.perf_counter()
        for c in chunks:
            sks.submit(c)
        sks.flush()
        tpu_t = min(tpu_t, time.perf_counter() - t0)
    dev_store = sks
    shard_secs = sks.host_secs_per_shard()  # last run (reset clears)
    folds = sum(s.get("folds", 0) for s in shard_secs)
    bytes_h2d = sum(s.get("bytes_h2d", 0) for s in shard_secs)
    bytes_d2h = sum(s.get("bytes_d2h", 0) for s in shard_secs)
    fam = {}
    stg = {}
    for s in shard_secs:
        for k, v in s.get("family_secs", {}).items():
            fam[k] = fam.get(k, 0.0) + v
        for k, v in s.get("stage_secs", {}).items():
            stg[k] = stg.get(k, 0.0) + v
    pipeline = os.environ.get("CONSTDB_PIPELINE", "1") != "0"
    rate = n_keys / tpu_t
    # wake the (pre-forked, idle) oracle worker NOW: its CPU replay
    # overlaps the merge epilogue (link probe + device-store canonical
    # extraction) instead of running serially after everything else
    oracle_err = None
    if oracle is not None:
        try:
            oracle[1].send("go")
        except OSError as e:  # worker died (e.g. OOM) during the runs —
            # the measured numbers must still reach the JSON line
            oracle_err = str(e) or type(e).__name__
            print(f"[bench] WARNING: verify worker died before go ({e}); "
                  f"verification unavailable", file=sys.stderr)
    t_verify0 = time.perf_counter()
    print(f"[bench] device engine (resident, {jax.default_backend()}, "
          f"group={group}, shards={shards}, folds={folds}): "
          f"{tpu_t:.3f}s on {n_keys} keys = {rate:,.0f} keys/s",
          file=sys.stderr)
    if fam:
        breakdown = " ".join(f"{k}={v:.3f}s" for k, v in sorted(fam.items()))
        print(f"[bench] stage breakdown (last run, critical-path host "
              f"times; flush includes blocking downloads): {breakdown}",
              file=sys.stderr)
    if stg and pipeline:
        overlapped = " ".join(f"{k}={v:.3f}s" for k, v in sorted(stg.items()))
        print(f"[bench] staging (background worker, overlaps device "
              f"compute — NOT additive with the breakdown above): "
              f"{overlapped}", file=sys.stderr)

    out = {
        "metric": "snapshot_merge_keys_per_sec",
        "value": round(rate, 1),
        "unit": "keys/sec",
        "vs_baseline": round(rate / cpu_rate, 2),
        "keys": n_keys,
        "replicas": n_rep,
        "wall_s": round(tpu_t, 2),
        "folds": folds,
        "backend": jax.default_backend(),
        "host_secs": {k: round(v, 3) for k, v in sorted(fam.items())},
        "stage_secs": {k: round(v, 3) for k, v in sorted(stg.items())},
        "pipeline": pipeline,
        "shards": shards,
        "host": host_fingerprint(),
    }
    # per-shard host seconds: the whole point of the sharded merge is
    # that cnt/el/flush SPLIT — make that visible per worker (length 1
    # when the degenerate single-shard path ran)
    out["shard_host_secs"] = [
        {k: round(v, 3) for k, v in sorted(s["family_secs"].items())}
        for s in shard_secs]
    out["shard_stage_secs"] = [
        {k: round(v, 3) for k, v in sorted(s["stage_secs"].items())}
        for s in shard_secs]

    # ------- measured link ceiling: what fraction of the wall is transfer
    up_bw, down_bw = probe_link(jax)
    link_secs = bytes_h2d / up_bw + bytes_d2h / down_bw
    out["bytes_h2d"] = bytes_h2d
    out["bytes_d2h"] = bytes_d2h
    out["link_bw_up_mbps"] = round(up_bw / 1e6, 1)
    out["link_bw_down_mbps"] = round(down_bw / 1e6, 1)
    out["link_secs"] = round(link_secs, 2)
    # fraction of the wall explained by moving this run's bytes at the
    # MEASURED link bandwidth; the reciprocal rate is the link-imposed
    # ceiling for this byte footprint
    out["pct_of_link_ceiling"] = round(link_secs / tpu_t, 3)
    if link_secs > 0:
        out["ceiling_keys_per_sec"] = round(n_keys / link_secs, 1)
    print(f"[bench] link: up {up_bw / 1e6:,.0f} MB/s down "
          f"{down_bw / 1e6:,.0f} MB/s; moved h2d "
          f"{bytes_h2d / 1e6:,.0f} MB d2h {bytes_d2h / 1e6:,.0f} MB "
          f"-> link floor {link_secs:.1f}s of {tpu_t:.1f}s wall "
          f"({100 * link_secs / tpu_t:.0f}%)", file=sys.stderr)

    # ------- on-hardware correctness: oracle-verify a ~100k-key subsample.
    # The oracle replay has been running in the forked worker since right
    # after the timed merge; the parent extracts the device store's
    # canonical slice in parallel and only then joins.
    verified = None
    if verify_on:
        sub_keys = subsample_keys(batches[0].keys, n_keys)
        got = dev_store.canonical(keys=sub_keys)
        n_diff = None
        if oracle_err is not None:
            out["verify_error"] = oracle_err
        elif oracle is not None:
            p, rx = oracle
            try:
                want = rx.recv()
            except (EOFError, OSError) as e:
                # a killed worker (e.g. OOM) must not cost the whole run's
                # JSON line — record verification as unavailable instead
                want = e
            finally:
                p.join()
            if isinstance(want, BaseException):
                # same protection for an error the worker itself hit and
                # shipped back (e.g. MemoryError mid-replay)
                print(f"[bench] WARNING: verify worker failed "
                      f"({type(want).__name__}: {want}); verification "
                      f"unavailable", file=sys.stderr)
                out["verify_error"] = \
                    f"{type(want).__name__}: {want}" .strip(": ")
            else:
                n_diff = compare_canonical(got, want)
        else:  # pragma: no cover - fork unavailable
            n_diff = compare_canonical(got, oracle_canonical(batches, n_keys))
        verified = None if n_diff is None else n_diff == 0
        if verified is not None:
            print(f"[bench] verify: {'OK' if verified else 'MISMATCH'} on "
                  f"{len(sub_keys)} sampled keys ({n_diff} diffs, "
                  f"{time.perf_counter() - t_verify0:.1f}s overlapped with "
                  f"the epilogue)", file=sys.stderr)
        out["verified"] = verified
        out["verify_keys"] = len(sub_keys)

    if jax.default_backend() == "tpu":
        out["link_note"] = "tunnel-attached chip: wall time is host-link " \
            "bandwidth bound, not VPU bound"
    if note:
        out["note"] = note
    dev_store.close()  # shard workers / engine pools
    print(json.dumps(out))
    if verified is False:
        sys.exit(1)


if __name__ == "__main__":
    main()
