#!/usr/bin/env bash
# Invariant lint in baseline mode: fails only on findings NOT recorded
# in constdb_tpu/analysis/baseline.json (growth).  Rule ↔ incident map:
# docs/INVARIANTS.md.  Extra args pass through (e.g. --write-baseline,
# explicit paths, --list-rules).
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m constdb_tpu.analysis --baseline "$@"
