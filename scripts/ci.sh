#!/usr/bin/env bash
# The full gate, one command:
#   1. invariant lint (baseline mode)        — scripts/lint.sh
#   2. tier-1 suite + slow-marker audit      — scripts/audit_markers.sh
#      (same pytest selection as scripts/t1.sh, plus the per-test
#      budget check, so the suite runs ONCE for both purposes)
# Exit code is the first failure's; each stage prints its own verdict.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== lint (baseline mode) =="
./scripts/lint.sh || exit $?

echo
echo "== lint baseline ratchet =="
# Retired debt must not silently regrow: the committed baseline's total
# finding count may only go DOWN.  PR 4 retired the last 17 findings
# (13 STAGE-PURE fold-stack builds, 4 ASYNC-BLOCK spill opens), so the
# ratchet sits at zero — any future baselined finding needs this number
# raised in review, on purpose.
python - <<'EOF' || exit $?
import json, sys
MAX_BASELINED = 0
base = json.load(open("constdb_tpu/analysis/baseline.json"))
total = sum(base.get("findings", {}).values())
print(f"baselined findings: {total} (ratchet: {MAX_BASELINED})")
if total > MAX_BASELINED:
    print("ci.sh: baseline GREW past the ratchet — fix the findings or "
          "raise MAX_BASELINED in scripts/ci.sh deliberately")
    sys.exit(1)
EOF

echo
echo "== tier-1 tests + slow-marker audit =="
./scripts/audit_markers.sh "$@" || exit $?

echo
echo "ci.sh: all gates green"
