#!/usr/bin/env bash
# The full gate, one command:
#   1. invariant lint (baseline mode)        — scripts/lint.sh
#   2. tier-1 suite + slow-marker audit      — scripts/audit_markers.sh
#      (same pytest selection as scripts/t1.sh, plus the per-test
#      budget check, so the suite runs ONCE for both purposes)
# Exit code is the first failure's; each stage prints its own verdict.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== lint (baseline mode) =="
./scripts/lint.sh || exit $?

echo
echo "== lint baseline ratchet =="
# Retired debt must not silently regrow: the committed baseline's total
# finding count may only go DOWN.  PR 4 retired the last 17 findings
# (13 STAGE-PURE fold-stack builds, 4 ASYNC-BLOCK spill opens), so the
# ratchet sits at zero — any future baselined finding needs this number
# raised in review, on purpose.
python - <<'EOF' || exit $?
import json, sys
MAX_BASELINED = 0
base = json.load(open("constdb_tpu/analysis/baseline.json"))
total = sum(base.get("findings", {}).values())
print(f"baselined findings: {total} (ratchet: {MAX_BASELINED})")
if total > MAX_BASELINED:
    print("ci.sh: baseline GREW past the ratchet — fix the findings or "
          "raise MAX_BASELINED in scripts/ci.sh deliberately")
    sys.exit(1)
EOF

echo
echo "== serve-shards smoke (bench --mode serve --serve-shards 2) =="
# tiny oracle-verified run of the shard-per-core serving plane over
# real sockets: reply streams + visible-value export of every shard
# count must match the shards=1 leg (the differential suite proper runs
# inside tier-1 — tests/test_serve_shards.py)
JAX_PLATFORMS=cpu CONSTDB_BENCH_SERVE_OPS=3000 CONSTDB_BENCH_SERVE_CONNS=2 \
CONSTDB_BENCH_SERVE_REPS=1 \
    timeout -k 10 300 python bench.py --mode serve --serve-shards 2 \
    > /tmp/_ci_serve_shards.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_serve_shards.json"))
assert out["verified"], "serve-shards smoke failed oracle verification"
print("serve-shards smoke verified:",
      [(leg["serve_shards"], leg["rps"]) for leg in out["serve_shards_curve"]])
EOF

echo
echo "== resync smoke (bench --mode resync) =="
# tiny oracle-verified run of the digest-negotiated delta resync vs the
# full-snapshot leg through the REAL push loop: both pullers must
# converge to the pusher's canonical export + full-state digest at
# every divergence fraction (the differential suite proper runs inside
# tier-1 — tests/test_delta_sync.py)
JAX_PLATFORMS=cpu CONSTDB_BENCH_RESYNC_KEYS=20000 \
CONSTDB_BENCH_RESYNC_VERIFY=5000 CONSTDB_BENCH_RESYNC_FRACS=0.01 \
    timeout -k 10 300 python bench.py --mode resync \
    > /tmp/_ci_resync.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_resync.json"))
assert out["verified"], "resync smoke failed oracle verification"
print("resync smoke verified:",
      [(leg["frac"], leg["bytes_ratio"]) for leg in out["curve"]])
EOF

echo
echo "== tier-1 tests + slow-marker audit =="
./scripts/audit_markers.sh "$@" || exit $?

echo
echo "ci.sh: all gates green"
