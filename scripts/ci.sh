#!/usr/bin/env bash
# The full gate, one command:
#   1. invariant lint (baseline mode)        — scripts/lint.sh
#   2. tier-1 suite + slow-marker audit      — scripts/audit_markers.sh
#      (same pytest selection as scripts/t1.sh, plus the per-test
#      budget check, so the suite runs ONCE for both purposes)
# Exit code is the first failure's; each stage prints its own verdict.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== lint (baseline mode) =="
./scripts/lint.sh || exit $?

echo
echo "== lint baseline ratchet =="
# Retired debt must not silently regrow.  PR 4 retired the last 17
# per-node findings, so the per-node rules ratchet at ZERO: any
# baselined finding from them fails here.  The flow-sensitive rules
# (AWAIT-ATOMICITY / LOCK-DISCIPLINE / CUT-ORDERING) reason about
# interleavings, so a deliberate, documented exception is a legitimate
# outcome — for THOSE rules only, a baselined key is allowed iff it
# carries a tracking note in baseline.json's notes map (a muted alarm
# nobody can explain is still a failure).  The live tree is currently
# clean either way; this gate is what keeps new debt honest.
python - <<'EOF' || exit $?
import json, sys
FLOW_RULES = ("AWAIT-ATOMICITY", "LOCK-DISCIPLINE", "CUT-ORDERING")
base = json.load(open("constdb_tpu/analysis/baseline.json"))
findings = base.get("findings", {})
notes = base.get("notes", {})
bad = []
for key in sorted(findings):
    rule = key.split(":", 1)[0]
    if rule not in FLOW_RULES:
        bad.append(f"  {key}\n    per-node rules ratchet at zero — fix "
                   f"the finding, do not baseline it")
    elif not any(key.startswith(p) for p in notes):
        bad.append(f"  {key}\n    baselined flow finding has no tracking "
                   f"note (add one under notes in baseline.json)")
flow = sum(v for k, v in findings.items()
           if k.split(":", 1)[0] in FLOW_RULES)
print(f"baselined findings: {sum(findings.values())} "
      f"({flow} noted flow-rule, ratchet: 0 for all other rules)")
if bad:
    print("ci.sh: baseline violates the ratchet:")
    print("\n".join(bad))
    sys.exit(1)
EOF

echo
echo "== sanitizer fuzz gate (make -C native san + scripts/fuzz_native.py) =="
# Memory-safety smoke for the four untrusted-byte C scanners
# (resp_parse, intake_scan, wire blob pack/unpack, aof_scan): rebuild
# the extension under ASan+UBSan (native/build/san/, never installed
# into the package) and replay the tier-1 fuzz corpora plus seeded
# mutations through it — any sanitizer report aborts the driver
# non-zero.  The sanitized .so links its runtimes dynamically, so the
# gate needs the toolchain's libasan/libubsan; where they are missing
# the stage SKIPS LOUDLY rather than pretending the check ran.
SAN_LIBS=""
if command -v g++ >/dev/null 2>&1; then
    for lib in libasan.so libubsan.so; do
        p="$(g++ -print-file-name=$lib 2>/dev/null)"
        [ -n "$p" ] && [ "$p" != "$lib" ] && [ -e "$p" ] && \
            SAN_LIBS="$SAN_LIBS $p"
    done
fi
if [ "$(echo $SAN_LIBS | wc -w)" -ne 2 ]; then
    echo "ci.sh: SKIPPING sanitizer fuzz gate — this toolchain lacks the"
    echo "       dynamic ASan/UBSan runtimes (found:${SAN_LIBS:- none})."
    echo "       The untrusted-byte scanners are NOT memory-checked on"
    echo "       this builder; run ci.sh where g++ ships libasan+libubsan."
else
    make -s -C native san || exit $?
    LD_PRELOAD="${SAN_LIBS# }" ASAN_OPTIONS=detect_leaks=0 \
    JAX_PLATFORMS=cpu timeout -k 10 420 python scripts/fuzz_native.py || {
        echo "ci.sh: sanitizer fuzz gate FAILED — ASan/UBSan report (or"
        echo "       driver error) replaying the scanner corpora; rerun"
        echo "       scripts/fuzz_native.py under the LD_PRELOAD above to"
        echo "       reproduce deterministically"
        exit 1
    }
fi

echo
echo "== native intake smoke (make -C native + bench --mode intake) =="
# the C intake plane end to end: rebuild the extension from source (the
# ABI stamp in the .so refuses stale builds loudly), then a tiny
# oracle-verified run of the three serve legs over real sockets — C
# intake stage / pure-Python drain (CONSTDB_NATIVE_INTAKE=0) / full
# fallback (CONSTDB_NO_NATIVE=1) — plus the REPLBATCH codec legs
# (native pack/unpack vs pure, encoded bytes byte-identical).  Reply
# streams and stripped exports must match across ALL legs and the
# native leg must PROVE it engaged (INFO gauge native_intake_chunks);
# the differential suites proper run inside tier-1
# (tests/test_native_intake.py / tests/test_resp_fuzz.py).
make -s -C native || exit $?
JAX_PLATFORMS=cpu CONSTDB_BENCH_SERVE_OPS=6000 CONSTDB_BENCH_SERVE_CONNS=2 \
CONSTDB_BENCH_SERVE_REPS=1 CONSTDB_BENCH_INTAKE_FRAMES=6000 \
    timeout -k 10 300 python bench.py --mode intake \
    > /tmp/_ci_intake.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_intake.json"))
assert out["verified"], "intake smoke failed oracle verification"
legs = out["legs"]
assert legs["native"]["native_intake_chunks"] > 0, \
    "native intake never engaged"
assert legs["pure"]["native_intake_chunks"] == 0, \
    "pinned pure leg ran the native stage"
assert legs["nonative"]["native_intake_chunks"] == 0, \
    "CONSTDB_NO_NATIVE leg ran the native stage"
for name, leg in legs.items():
    assert leg["replies_ok"] and leg["export_ok"], \
        f"intake leg {name} diverged from the native reference"
assert out["wire"]["verified"], "wire codec legs mismatched"
print("intake smoke verified:",
      f"{legs['native']['rps']:,.0f} req/s native /",
      f"{legs['pure']['rps']:,.0f} pure /",
      f"{legs['nonative']['rps']:,.0f} no-native,",
      f"{legs['native']['native_intake_chunks']} native chunks,",
      f"wire {out['wire']['encode_speedup']}x enc "
      f"{out['wire']['decode_speedup']}x dec")
EOF
# the stream smoke's fallback leg: the same wire protocol run with NO
# native tier anywhere (CONSTDB_NO_NATIVE=1) must still pass its full
# oracle — pure pack/unpack is the reference the native codec is pinned
# against, so a fallback regression fails here, not in production
JAX_PLATFORMS=cpu CONSTDB_NO_NATIVE=1 CONSTDB_BENCH_FRAMES=3000 \
CONSTDB_BENCH_WIRE_REPS=1 \
    timeout -k 10 300 python bench.py --mode stream --wire \
    > /tmp/_ci_wire_nonative.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_wire_nonative.json"))
assert out["verified"], "CONSTDB_NO_NATIVE wire smoke failed its oracle"
print("no-native wire smoke verified:",
      f"batch leg {out['legs'][0]['fps']} fps, pure codec end to end")
EOF

echo
echo "== serve-shards smoke (bench --mode serve --serve-shards 2) =="
# tiny oracle-verified run of the shard-per-core serving plane over
# real sockets: reply streams + visible-value export of every shard
# count must match the shards=1 leg (the differential suite proper runs
# inside tier-1 — tests/test_serve_shards.py)
JAX_PLATFORMS=cpu CONSTDB_BENCH_SERVE_OPS=3000 CONSTDB_BENCH_SERVE_CONNS=2 \
CONSTDB_BENCH_SERVE_REPS=1 \
    timeout -k 10 300 python bench.py --mode serve --serve-shards 2 \
    > /tmp/_ci_serve_shards.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_serve_shards.json"))
assert out["verified"], "serve-shards smoke failed oracle verification"
print("serve-shards smoke verified:",
      [(leg["serve_shards"], leg["rps"]) for leg in out["serve_shards_curve"]])
EOF

echo
echo "== cluster smoke (bench --mode cluster) =="
# tiny oracle-verified run of the hash-slot partitioning legs: the
# same op stream partitioned by slot owner must union back to the
# single group's visible-value export with zero redirects (client
# partitioning and server routing agree on the slot math), the
# redirect-tax pair must match reply-for-reply, and a live slot-range
# migration must flip ownership with the moved keys serving from the
# target at O(slot bytes) shipped (the differential suite proper runs
# inside tier-1 — tests/test_cluster.py; the partition/flap/
# resurrection convergence cells run in the chaos smoke below)
JAX_PLATFORMS=cpu CONSTDB_BENCH_CLUSTER_OPS=4000 \
CONSTDB_BENCH_CLUSTER_CONNS=2 CONSTDB_BENCH_CLUSTER_GROUPS=2 \
CONSTDB_BENCH_CLUSTER_REPS=1 CONSTDB_BENCH_CLUSTER_TAX_REPS=1 \
CONSTDB_BENCH_CLUSTER_MIG_KEYS=2000 CONSTDB_BENCH_CLUSTER_MIG_SLOTS=16 \
    timeout -k 10 300 python bench.py --mode cluster \
    > /tmp/_ci_cluster.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_cluster.json"))
assert out["verified"], "cluster smoke failed oracle verification"
mig = out["migration"]
assert mig["ok"] and mig["slots"] == 16, mig
assert mig["shipped_vs_full"] < 0.25, \
    f"migration shipped {mig['shipped_vs_full']:.0%} of the full state"
assert out["route_check_pct_of_op"] < 5.0, \
    f"route check at {out['route_check_pct_of_op']}% of the op budget"
print("cluster smoke verified:",
      f"{out['groups']} groups {out['value']}x,",
      f"route check {out['route_check_ns']}ns,",
      f"migration {mig['slots']} slots =",
      f"{mig['shipped_vs_full']:.1%} of full state shipped")
EOF

echo
echo "== read-path smoke (bench --mode serve --read-pct 90) =="
# tiny oracle-verified run of the coalesced read plane over real
# sockets: a mixed 90:10 pipelined workload on the coalesced+cache,
# cache-off, and per-command legs — every reply stream and the
# timestamp-stripped export must match the per-command reference
# byte-for-byte (a stale cached serve is an oracle MISMATCH, not a
# slowdown), the read planner must actually engage, and the cache must
# serve real hits (the differential suite proper runs inside tier-1 —
# tests/test_read_path.py)
JAX_PLATFORMS=cpu CONSTDB_BENCH_SERVE_OPS=6000 CONSTDB_BENCH_SERVE_CONNS=2 \
CONSTDB_BENCH_SERVE_REPS=1 \
    timeout -k 10 300 python bench.py --mode serve --read-pct 90 \
    > /tmp/_ci_read.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_read.json"))
assert out["verified"], "read-path smoke failed oracle verification"
leg = out["curve"][0]
assert leg["cache"]["replies_ok"] and leg["nocache"]["replies_ok"], \
    "stale replies on a coalesced read leg"
assert leg["cache"]["serve_reads_coalesced"] > 0, \
    "read planner never engaged"
assert leg["cache"]["read_cache_hits"] > 0, "reply cache never hit"
assert leg["nocache"]["read_cache_hits"] == 0, \
    "disabled cache served hits"
print("read-path smoke verified:",
      f"{leg['cache']['rps']:,.0f} req/s cached /",
      f"{leg['percmd']['rps']:,.0f} per-command =",
      f"{leg['speedup_vs_percmd']}x, hit rate {leg['cache_hit_rate']},",
      f"{leg['cache']['serve_reads_coalesced']} planned reads")
EOF

echo
echo "== tracking smoke (bench --mode tracked) =="
# tiny oracle-verified run of the client-assisted caching tier over
# real sockets: K tracked RESP3 near-cache clients vs K plain clients
# on the same deterministic hot-key 90:10 storm.  The server must have
# actually pushed invalidations (tracking_invalidations_sent > 0, no
# loud demotions), every entry still resident in a near-cache at
# quiesce must equal a direct server read (zero-stale), the stripped
# exports must match across legs, and the reads that reached the
# server must shrink by the advertised floor (the unit/property suites
# proper run inside tier-1 — tests/test_tracking.py /
# tests/test_resp_fuzz.py; the track-partition chaos cell rides the
# chaos smoke below, the full tracking cell set the slow matrix)
JAX_PLATFORMS=cpu CONSTDB_BENCH_TRACKED_OPS=8000 \
CONSTDB_BENCH_TRACKED_REPS=1 \
    timeout -k 10 300 python bench.py --mode tracked \
    > /tmp/_ci_tracked.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_tracked.json"))
assert out["verified"], "tracking smoke failed oracle verification"
trk = out["tracked"]
assert trk["tracking_invalidations_sent"] > 0, \
    "server never pushed an invalidation"
assert trk["tracking_demotions"] == 0, "a tracker was demoted"
assert trk["stale_entries"] == 0, "near-cache served stale entries"
assert out["export_ok"], "tracked leg diverged from the plain leg"
assert out["value"] >= 5.0, \
    f"server-side read reduction collapsed: {out['value']}x"
print("tracking smoke verified:",
      f"{out['plain']['server_read_ops']} -> {trk['server_read_ops']}",
      f"server reads = {out['value']}x, hit rate",
      f"{trk['near_cache_hit_rate']},",
      f"{trk['tracking_invalidations_sent']} invalidations pushed")
EOF

echo
echo "== resync smoke (bench --mode resync) =="
# tiny oracle-verified run of the digest-negotiated delta resync vs the
# full-snapshot leg through the REAL push loop: both pullers must
# converge to the pusher's canonical export + full-state digest at
# every divergence fraction (the differential suite proper runs inside
# tier-1 — tests/test_delta_sync.py)
JAX_PLATFORMS=cpu CONSTDB_BENCH_RESYNC_KEYS=20000 \
CONSTDB_BENCH_RESYNC_VERIFY=5000 CONSTDB_BENCH_RESYNC_FRACS=0.01 \
    timeout -k 10 300 python bench.py --mode resync \
    > /tmp/_ci_resync.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_resync.json"))
assert out["verified"], "resync smoke failed oracle verification"
print("resync smoke verified:",
      [(leg["frac"], leg["bytes_ratio"]) for leg in out["curve"]])
EOF

echo
echo "== wire smoke (bench --mode stream --wire) =="
# tiny oracle-verified run of the batch wire protocol over a real
# socket pair: REPLBATCH legs vs the per-frame wire on the same frame
# log, both receivers byte-identical to the per-frame CPU oracle, the
# 3-node mesh differential converged, and the columnar payload actually
# paying for itself on the wire (the differential suite proper runs
# inside tier-1 — tests/test_wire_batch.py / test_repl_capabilities.py)
JAX_PLATFORMS=cpu CONSTDB_BENCH_FRAMES=5000 CONSTDB_BENCH_WIRE_REPS=1 \
    timeout -k 10 300 python bench.py --mode stream --wire \
    > /tmp/_ci_wire.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_wire.json"))
assert out["verified"], "wire smoke failed oracle verification"
assert out["wire_bytes_ratio"] >= 2.0, \
    f"columnar wire stopped paying: {out['wire_bytes_ratio']}x bytes"
assert out["mesh_differential"]["converged"], "wire mesh diverged"
assert out["legs"][0]["wire_demotions"] == 0, "wire codec demoted"
print("wire smoke verified:",
      f"{out['speedup_vs_per_frame_wire']}x frames/s,",
      f"{out['wire_bytes_ratio']}x wire bytes,",
      f"batch leg {out['legs'][0]['fps']} fps")
EOF

echo
echo "== broadcast smoke (bench --mode stream --peers 4) =="
# tiny oracle-verified run of the broadcast plane: one pusher fanning
# out to 4 peers with the encode-once cache on vs off (every peer's
# captured stream applied + export-compared against the per-frame CPU
# oracle), plus the compressed-vs-plain bulk-sync bytes leg (the
# differential suites proper run inside tier-1 —
# tests/test_encode_cache.py / tests/test_wire_compress.py)
JAX_PLATFORMS=cpu CONSTDB_BENCH_FRAMES=5000 CONSTDB_BENCH_FANOUT_REPS=1 \
CONSTDB_BENCH_FSYNC_KEYS=20000 CONSTDB_BENCH_FSYNC_REPLICAS=2 \
    timeout -k 10 300 python bench.py --mode stream --peers 4 \
    > /tmp/_ci_fanout.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_fanout.json"))
assert out["verified"], "broadcast smoke failed oracle verification"
top = out["curve"][-1]
assert top["cache_on"]["cache_hit_rate"] >= 0.7, \
    f"encode-once reuse collapsed: {top['cache_on']['cache_hit_rate']}"
assert top["speedup_vs_cache_off"] >= 1.5, \
    f"fan-out stopped paying: {top['speedup_vs_cache_off']}x"
fs = out["fullsync"]
assert fs["bytes_ratio_vs_uncompressed"] <= 0.4, \
    f"bulk compression stopped paying: {fs['bytes_ratio_vs_uncompressed']}"
print("broadcast smoke verified:",
      f"{top['speedup_vs_cache_off']}x agg fan-out at 4 peers,",
      f"hit rate {top['cache_on']['cache_hit_rate']},",
      f"bulk {fs['bytes_ratio_vs_uncompressed']}x of uncompressed")
EOF

echo
echo "== resident smoke (pallas-interpret snapshot + stream) =="
# tiny oracle-verified runs of the device-resident steady path with the
# Pallas kernels forced through the interpreter: a kernel that drifts
# from the host semantics fails HERE on CPU-only builders, not on the
# first real-TPU round.  Snapshot leg = bulk catch-up through the fold
# kernels; stream leg = in-place micro merges through the resident
# scatter kernels (the differential suite proper runs inside tier-1 —
# tests/test_resident_steady.py / tests/test_pallas_dense.py).
JAX_PLATFORMS=cpu CONSTDB_BENCH_KEYS=20000 CONSTDB_BENCH_REPLICAS=2 \
CONSTDB_BENCH_CPU_KEYS=5000 CONSTDB_BENCH_FOLD=pallas-interpret \
    timeout -k 10 300 python bench.py --mode snapshot --resident 1 \
    > /tmp/_ci_resident_snap.json || exit $?
JAX_PLATFORMS=cpu CONSTDB_BENCH_FRAMES=3000 CONSTDB_BENCH_STREAM_KEYS=500 \
CONSTDB_BENCH_APPLY_BATCH=256 CONSTDB_BENCH_FOLD=pallas-interpret \
    timeout -k 10 300 python bench.py --mode stream --resident 1 \
    > /tmp/_ci_resident_stream.json || exit $?
python - <<'EOF' || exit $?
import json
snap = json.load(open("/tmp/_ci_resident_snap.json"))
assert snap["verified"], "resident snapshot smoke failed oracle verification"
stream = json.load(open("/tmp/_ci_resident_stream.json"))
assert stream["verified"], "resident stream smoke failed oracle verification"
leg = stream["resident_curve"][0]
assert leg["dev_rounds_resident"] > 0, "steady path never engaged"
assert not leg["pallas_broken"], "pallas kernels fell back to XLA"
assert 0 < leg["flush_rows_downloaded"] < leg["flush_rows_full_equiv"], \
    "flush downloads were not partial"
print("resident smoke verified: snapshot",
      snap["resident_curve"][0]["keys_per_sec"], "keys/s; stream",
      leg["fps"], "fps,", leg["dev_rounds_resident"], "resident rounds,",
      f"{leg['flush_rows_downloaded']}/{leg['flush_rows_full_equiv']}",
      "rows flushed")
EOF

echo
echo "== tensor smoke (bench --mode tensor, pallas-interpret) =="
# tiny oracle-verified run of the tensor-register family with the
# reduce kernels forced through the Pallas interpreter: device-resident
# merges + reads must stay BIT-identical to the host reference (the
# canonical-order law) and the steady path must actually engage
# (dev_rounds_resident / tns_dev_rows) — the differential suite proper
# runs inside tier-1 (tests/test_tensor_family.py).
JAX_PLATFORMS=cpu CONSTDB_BENCH_TNS_KEYS=8 CONSTDB_BENCH_TNS_ELEMS=4096 \
CONSTDB_BENCH_TNS_ROUNDS=6 CONSTDB_BENCH_TNS_BATCH=32 \
CONSTDB_BENCH_TNS_REPS=1 CONSTDB_BENCH_TNS_STRATS=avg,trimmed-mean \
CONSTDB_BENCH_FOLD=pallas-interpret \
    timeout -k 10 300 python bench.py --mode tensor \
    > /tmp/_ci_tensor.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_tensor.json"))
assert out["verified"], "tensor smoke failed oracle verification"
for leg in out["curve"]:
    assert leg["dev_rounds_resident"] > 0, \
        f"tensor steady path never engaged ({leg['strategy']})"
    assert leg["tns_dev_rows"] > 0 and leg["tns_host_rows"] == 0, \
        f"tensor rows did not ride the device path ({leg['strategy']})"
    assert not leg["pallas_broken"], "pallas tensor kernels fell back"
print("tensor smoke verified:",
      [(leg["strategy"], leg["speedup"]) for leg in out["curve"]])
EOF

echo
echo "== overload smoke (bench --mode serve --overload + chaos resource cells) =="
# a memory-capped node under a firehose pipeline: survives, sheds with
# the exact -OOM error, non-shed reply latency stays bounded, and the
# accounting gauges match the pressure (server/overload.py).  Then the
# chaos resource cells certify the convergence half: shed writes were
# never partially applied or replicated, replication intake stayed
# admitted, a peer converges byte-identical to the CPU reference, a
# stalled client is cut at the outbuf cap, and a stalled peer recovers
# through the repl-window pause -> eviction -> resync path.
JAX_PLATFORMS=cpu CONSTDB_BENCH_OVL_OPS=12000 \
    timeout -k 10 300 python bench.py --mode serve --overload \
    > /tmp/_ci_overload.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_overload.json"))
assert out["verified"], "overload smoke failed verification"
assert out["survived"] and out["other_errors"] == 0
assert out["shed"] > 0 and out["landed"] > 0, "no shed/landed split"
assert out["reply_p99_ms"] < 1000, \
    f"non-shed p99 {out['reply_p99_ms']}ms — shedding is livelocking"
print("overload smoke verified:",
      f"{out['value']:.0%} shed at {out['rps']} req/s,",
      f"p99 {out['reply_p99_ms']}ms, state {out['overload_state']}")
EOF
JAX_PLATFORMS=cpu timeout -k 10 300 python -m constdb_tpu.chaos \
    --resource --seed 7 || exit $?

echo
echo "== durability smoke (AOF kill -9 + bench --mode serve --aof) =="
# a REAL server process with the durable op log under fsync=always:
# firehose it over a socket, kill -9 mid-stream, restart from the
# node's own log, and oracle-compare — every acknowledged write must
# be present (or superseded by a LATER write of the same key that also
# survived), the recovery gauges must report the replay, and a second
# clean restart must be idempotent.  Then the tiny bench legs verify
# off/everysec/always exports match and the recovery replay
# round-trips (tests/test_oplog.py runs the differential suites in
# tier-1; the chaos kill9/torn cells run in the chaos smoke below).
JAX_PLATFORMS=cpu timeout -k 10 300 python - <<'EOF' || exit $?
import asyncio, os, signal, socket, subprocess, sys, tempfile, time

async def main():
    with tempfile.TemporaryDirectory(prefix="constdb-dur-") as work:
        s = socket.socket(); s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]; s.close()
        args = [sys.executable, "-m", "constdb_tpu.bin.server",
                "--port", str(port), "--work-dir", work,
                "--aof", "--aof-fsync", "always", "--node-id", "1"]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(args, env=env)
        from constdb_tpu.chaos.cluster import Client
        c = Client()
        for _ in range(100):
            try:
                await c.connect(f"127.0.0.1:{port}"); break
            except OSError:
                await asyncio.sleep(0.1)
        else:
            raise SystemExit("server never came up")
        # firehose: sequential acked writes (the client-side journal),
        # then a pipelined burst we kill the server in the middle of
        acked = {}
        for i in range(400):
            k = f"k{i % 16}"
            r = await c.cmd("set", k, f"v{i:06d}")
            acked[k] = i
        from constdb_tpu.resp.codec import encode_msg
        from constdb_tpu.resp.message import Arr, Bulk
        buf = bytearray()
        for i in range(400, 2400):
            buf += encode_msg(Arr([Bulk(b"set"), Bulk(b"k%d" % (i % 16)),
                                   Bulk(b"v%06d" % i)]))
        c.writer.write(bytes(buf))
        await c.writer.drain()
        # count replies until the kill lands mid-stream (the short
        # sleep lets the server get INTO the burst first, so the kill
        # really is mid-write, not before it)
        got = 0
        t0 = time.monotonic()
        await asyncio.sleep(0.05)
        os.kill(proc.pid, signal.SIGKILL)
        try:
            while got < 2000 and time.monotonic() - t0 < 5:
                data = await asyncio.wait_for(c.reader.read(1 << 16), 2.0)
                if not data:
                    break
                c.parser.feed(data)
                while c.parser.next_msg() is not None:
                    acked[f"k{(400 + got) % 16}"] = 400 + got
                    got += 1
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
        proc.wait(timeout=10)
        print(f"[smoke] killed -9 mid-firehose after {400 + got} acked "
              f"writes")
        # cold restart: recovery from the node's own log
        proc = subprocess.Popen(args, env=env)
        c2 = Client()
        for _ in range(150):
            try:
                await c2.connect(f"127.0.0.1:{port}"); break
            except OSError:
                await asyncio.sleep(0.1)
        else:
            raise SystemExit("server never came back after kill -9")
        lost = []
        for k, serial in acked.items():
            r = await c2.cmd("get", k)
            v = r.val.decode() if hasattr(r, "val") and r.val else ""
            if not v.startswith("v") or int(v[1:]) < serial:
                lost.append((k, serial, v))
        assert not lost, f"acked writes lost after kill -9: {lost[:5]}"
        info = (await c2.cmd("info", "durability")).val.decode()
        assert "aof_enabled:1" in info
        assert "aof_recovery_source:log-only" in info, info
        ops = int(next(l for l in info.splitlines()
                       if l.startswith("aof_recovered_ops:"))
                  .split(":")[1])
        assert ops >= 400 + got, (ops, 400 + got)
        await c2.close()
        os.kill(proc.pid, signal.SIGTERM)
        proc.wait(timeout=15)
        print(f"[smoke] durability smoke verified: {ops} ops replayed, "
              f"zero acked writes lost")

asyncio.run(main())
EOF
JAX_PLATFORMS=cpu CONSTDB_BENCH_AOF_OPS=6000 CONSTDB_BENCH_SERVE_CONNS=2 \
CONSTDB_BENCH_AOF_REPS=1 \
    timeout -k 10 300 python bench.py --mode serve --aof \
    > /tmp/_ci_aof.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_aof.json"))
assert out["verified"], "aof bench legs failed oracle verification"
assert out["recovery_verified"], "aof recovery replay mismatched"
assert out["recovery_ops"] > 0
print("aof bench smoke verified:",
      [(leg["aof"], leg["rps"]) for leg in out["legs"]],
      f"recovery {out['recovery_s_per_gb']} s/GB")
EOF

echo
echo "== recovery smoke (parallel bulk-merge restart + checkpointed tail) =="
# the fast-restart plane end to end on a REAL server process: firehose
# acked writes over a socket, kill -9 mid-burst, and time the cold
# restart — the default parallel bulk-merge recovery must come up with
# ZERO acked writes lost and say so in the INFO Recovery gauges
# (recovery_mode/recovery_wall_s/recovery_merge_rounds).  Then an
# incremental-checkpoint phase (CONSTDB_CHECKPOINT_SECS cadence) cuts a
# mid-run checkpoint and proves the NEXT restart replays only the
# post-checkpoint tail, gauge-asserted (aof_recovered_ops collapses,
# checkpoint_last_uuid survives the restart).  The differential suites
# proper run inside tier-1 (tests/test_oplog.py); the crash-mid-
# checkpoint cells run in the chaos smoke below.
JAX_PLATFORMS=cpu timeout -k 10 420 python - <<'EOF' || exit $?
import asyncio, os, signal, socket, subprocess, sys, tempfile, time

async def connect(port, tries=150):
    from constdb_tpu.chaos.cluster import Client
    c = Client()
    for _ in range(tries):
        try:
            await c.connect(f"127.0.0.1:{port}")
            return c
        except OSError:
            await asyncio.sleep(0.1)
    raise SystemExit("server never came up")

async def info_map(c, section):
    raw = (await c.cmd("info", section)).val.decode()
    out = {}
    for line in raw.splitlines():
        if ":" in line and not line.startswith("#"):
            k, _, v = line.partition(":")
            out[k] = v.strip()
    return out

async def main():
    with tempfile.TemporaryDirectory(prefix="constdb-rec-") as work:
        s = socket.socket(); s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]; s.close()
        args = [sys.executable, "-m", "constdb_tpu.bin.server",
                "--port", str(port), "--work-dir", work,
                "--aof", "--aof-fsync", "always", "--node-id", "1"]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(args, env=env)
        c = await connect(port)
        # -- phase 1: acked firehose (mixed columnar shapes so the bulk
        # replay actually group-encodes), then a pipelined burst the
        # kill -9 lands inside
        acked = {}
        for i in range(300):
            k = f"k{i % 16}"
            await c.cmd("set", k, f"v{i:06d}")
            acked[k] = i
            if i % 3 == 0:
                await c.cmd("sadd", f"s{i % 8}", f"m{i}")
            elif i % 3 == 1:
                await c.cmd("hset", f"h{i % 8}", f"f{i % 5}", f"w{i}")
        from constdb_tpu.resp.codec import encode_msg
        from constdb_tpu.resp.message import Arr, Bulk
        buf = bytearray()
        for i in range(300, 2300):
            buf += encode_msg(Arr([Bulk(b"set"), Bulk(b"k%d" % (i % 16)),
                                   Bulk(b"v%06d" % i)]))
        c.writer.write(bytes(buf))
        await c.writer.drain()
        got = 0
        t0 = time.monotonic()
        await asyncio.sleep(0.05)
        os.kill(proc.pid, signal.SIGKILL)
        try:
            while got < 2000 and time.monotonic() - t0 < 5:
                data = await asyncio.wait_for(c.reader.read(1 << 16), 2.0)
                if not data:
                    break
                c.parser.feed(data)
                while c.parser.next_msg() is not None:
                    acked[f"k{(300 + got) % 16}"] = 300 + got
                    got += 1
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
        proc.wait(timeout=10)
        print(f"[smoke] killed -9 mid-firehose after {500 + got} acked "
              f"writes")
        # -- phase 2: timed cold restart through the default parallel
        # bulk-merge recovery; every acked write must be back
        t0 = time.monotonic()
        proc = subprocess.Popen(args, env=env)
        c2 = await connect(port)
        boot_wall = time.monotonic() - t0
        lost = []
        for k, serial in acked.items():
            r = await c2.cmd("get", k)
            v = r.val.decode() if hasattr(r, "val") and r.val else ""
            if not v.startswith("v") or int(v[1:]) < serial:
                lost.append((k, serial, v))
        assert not lost, f"acked writes lost after kill -9: {lost[:5]}"
        rec = await info_map(c2, "recovery")
        assert rec["recovery_mode"].startswith("bulk"), rec
        assert float(rec["recovery_wall_s"]) > 0, rec
        assert int(rec["recovery_merge_rounds"]) >= 1, rec
        dur = await info_map(c2, "durability")
        full_ops = int(dur["aof_recovered_ops"])
        assert full_ops >= 500 + got, (full_ops, 500 + got)
        await c2.close()
        os.kill(proc.pid, signal.SIGTERM)
        proc.wait(timeout=15)
        print(f"[smoke] parallel restart verified: {full_ops} ops "
              f"replayed in {rec['recovery_wall_s']}s "
              f"({rec['recovery_mode']}, {rec['recovery_merge_rounds']} "
              f"merge rounds; boot-to-serve {boot_wall:.2f}s), zero "
              f"acked writes lost")
        # -- phase 3: incremental checkpoints — run with a fast cadence
        # until a checkpoint cuts, append a small tail, and prove the
        # next clean restart replays ONLY the tail
        env_ck = dict(env, CONSTDB_CHECKPOINT_SECS="0.3",
                      CONSTDB_CHECKPOINT_MIN_MB="0")
        proc = subprocess.Popen(args, env=env_ck)
        c3 = await connect(port)
        ck_uuid = 0
        for i in range(200):
            await c3.cmd("set", f"ck{i % 8}", f"x{i:04d}")
            rec = await info_map(c3, "recovery")
            ck_uuid = int(rec.get("checkpoint_last_uuid", 0))
            if ck_uuid:
                break
            await asyncio.sleep(0.1)
        assert ck_uuid > 0, "checkpoint cadence never cut"
        assert float(rec["checkpoint_age_s"]) >= 0, rec
        for i in range(40):
            await c3.cmd("set", f"t{i}", f"y{i:04d}")
        await c3.close()
        os.kill(proc.pid, signal.SIGTERM)
        proc.wait(timeout=15)
        proc = subprocess.Popen(args, env=env)
        c4 = await connect(port)
        dur = await info_map(c4, "durability")
        tail_ops = int(dur["aof_recovered_ops"])
        assert dur["aof_recovery_source"].startswith("aof-base-snapshot"), \
            dur
        assert tail_ops < full_ops // 4, (tail_ops, full_ops)
        rec = await info_map(c4, "recovery")
        assert int(rec["checkpoint_last_uuid"]) > 0, rec
        v = (await c4.cmd("get", "t39")).val
        assert v == b"y0039", v
        await c4.close()
        os.kill(proc.pid, signal.SIGTERM)
        proc.wait(timeout=15)
        print(f"[smoke] checkpointed restart verified: {tail_ops} "
              f"tail ops replayed (vs {full_ops} full-log), "
              f"checkpoint uuid {ck_uuid} survived the restart")

asyncio.run(main())
EOF
JAX_PLATFORMS=cpu CONSTDB_BENCH_RECOVER_OPS=8000 \
CONSTDB_BENCH_RECOVER_REPS=1 \
    timeout -k 10 420 python bench.py --mode recover \
    > /tmp/_ci_recover.json || exit $?
python - <<'EOF' || exit $?
import json
out = json.load(open("/tmp/_ci_recover.json"))
assert out["verified"], "recover bench legs failed oracle verification"
legs = {leg["leg"]: leg for leg in out["legs"]}
assert legs["frames-bulk"]["byte_identical"], "bulk replay diverged"
assert legs["batch-bulk"]["byte_identical"], "batch bulk replay diverged"
assert legs["checkpointed-tail"]["tail_ops"] < out["ops"] // 4, \
    "checkpointed restart replayed more than the tail"
assert all(s["verified"] for s in out["shard_curve"]), \
    "sharded restart failed its oracle"
print("recover bench smoke verified:",
      f"frames {legs['frames-bulk']['speedup_vs_serial']}x,",
      f"batches {legs['batch-bulk']['speedup_vs_serial']}x,",
      f"tail {legs['checkpointed-tail']['tail_ops']} of",
      out["ops"], "ops")
EOF

echo
echo "== chaos smoke (fixed-seed certification cells) =="
# the scripted chaos scenario — partitions + reorder + duplication +
# mid-frame truncation + connection/process kills + clock jitter + one
# mixed-version peer — on one representative capability cell per fast
# path (everything-on, everything-off, resident engine, sharded
# serving, and the AOF always/everysec durability cells, whose
# schedules add kill9_mid_write + torn_write cold restarts recovering
# from the node's own op log), with the full invariant oracle verified:
# convergence to the
# CPU-engine reference, digest agreement, watermark monotonicity,
# no-resurrection, GC drain, and loud demotion accounting.  Fixed seed:
# a failure here replays exactly (the full matrix + randomized soak are
# slow-marked in tests/test_chaos.py).
JAX_PLATFORMS=cpu timeout -k 10 420 python -m constdb_tpu.chaos --seed 7 \
    || exit $?

echo
echo "== tier-1 tests + slow-marker audit =="
./scripts/audit_markers.sh "$@" || exit $?

echo
echo "ci.sh: all gates green"
