#!/usr/bin/env bash
# The full gate, one command:
#   1. invariant lint (baseline mode)        — scripts/lint.sh
#   2. tier-1 suite + slow-marker audit      — scripts/audit_markers.sh
#      (same pytest selection as scripts/t1.sh, plus the per-test
#      budget check, so the suite runs ONCE for both purposes)
# Exit code is the first failure's; each stage prints its own verdict.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== lint (baseline mode) =="
./scripts/lint.sh || exit $?

echo
echo "== tier-1 tests + slow-marker audit =="
./scripts/audit_markers.sh "$@" || exit $?

echo
echo "ci.sh: all gates green"
