#!/usr/bin/env bash
# Fast `-m 'not slow'` marker audit: run the tier-1 selection and FAIL if
# any test slower than the budget (CONSTDB_MARKER_AUDIT_BUDGET, default
# 5s) is missing the `slow` marker.  The measurement lives in
# tests/conftest.py (pytest_runtest_logreport), gated on the
# CONSTDB_MARKER_AUDIT env var; this script just supplies the report path
# and interprets it.  Extra pytest args pass through (e.g. a sub-path).
set -uo pipefail
cd "$(dirname "$0")/.."
report=$(mktemp /tmp/constdb_marker_audit.XXXXXX)
trap 'rm -f "$report"' EXIT
CONSTDB_MARKER_AUDIT="$report" JAX_PLATFORMS=cpu \
  python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly "$@"
rc=$?
if [ -s "$report" ]; then
  echo "MARKER AUDIT FAILED — unmarked tests over budget (add @pytest.mark.slow):" >&2
  cat "$report" >&2
  exit 1
fi
if [ $rc -ne 0 ]; then
  echo "marker audit: no unmarked slow tests, but the suite itself failed (rc=$rc)" >&2
  exit $rc
fi
echo "marker audit OK: no unmarked test over budget"
