#!/usr/bin/env python
"""Deterministic fuzz replay for the four untrusted-byte C scanners.

The native extension parses bytes that arrive from outside the trust
boundary — client sockets (resp_parse, intake_scan), peer replication
streams (wire_unpack_blobs) and on-disk op-log segments (aof_scan).  A
memory-safety bug in any of them is a remote crash primitive, and the
regular test suite runs them under a non-instrumented build where an
out-of-bounds read is usually silent.

This driver loads a SANITIZED build of the same single-TU extension
(`make -C native san` -> native/build/san/cst_ext.so, ASan+UBSan,
never copied into the package) by explicit path and replays:

  * the existing fuzz corpora — the same generators tier-1 uses
    (tests/test_resp_fuzz.py: rand_msg / rand_command, the malformed
    and absurd-header fixed cases), re-encoded with fixed seeds;
  * seeded mutations of every corpus buffer — bit flips, truncations,
    splices, inserts and deletes — so framing arithmetic sees torn and
    hostile inputs, not just well-formed ones;
  * structural edge cases per scanner (every prefix of a small wire,
    wrong counts/positions for the blob codec, torn + bit-flipped
    op-log segments in both raw and frame-decoding modes).

Python-level exceptions are FINE (that is the reject path under test);
the failure signal is the sanitizer itself — any ASan/UBSan report
aborts the process non-zero, which is what scripts/ci.sh gates on.

Run under the sanitizer runtime (the .so links it dynamically):

    LD_PRELOAD="$(g++ -print-file-name=libasan.so) \\
                $(g++ -print-file-name=libubsan.so)" \\
    ASAN_OPTIONS=detect_leaks=0 python scripts/fuzz_native.py

Deterministic by construction: fixed --seed, and no wall-clock or pid
inputs — a failing run replays exactly.
"""

import argparse
import importlib.util
import os
import random
import sys
import zlib

# The production extension must never load in this process: the package
# is imported only for message classes / encoders, and every native
# tier declines under CONSTDB_NO_NATIVE, so the sanitized module passed
# by path is the ONLY native code exercised.
os.environ["CONSTDB_NO_NATIVE"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from constdb_tpu.resp.codec import encode_into  # noqa: E402
from constdb_tpu.resp.message import (NIL, Arr, Bulk, Err, Int,  # noqa: E402
                                      Simple)

CLASSES = (Arr, Bulk, Int, Simple, Err, NIL)
MAX_BULK = 512 * 1024 * 1024


def load_sanitized_ext(path: str):
    spec = importlib.util.spec_from_file_location("cst_ext", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_fuzz_generators():
    """rand_msg / rand_command from tests/test_resp_fuzz.py — the
    corpora ARE the tier-1 generators, imported by path so this driver
    replays exactly what the differential suites feed."""
    path = os.path.join(REPO, "tests", "test_resp_fuzz.py")
    spec = importlib.util.spec_from_file_location("_resp_fuzz", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.rand_msg, mod.rand_command


# Fixed malformed / absurd-header cases (mirrors the tier-1 parametrized
# cases plus raw-garbage frames the generators cannot emit).
FIXED_CASES = (
    b"",
    b"\r\n",
    b"$-1\r\n",
    b"$0\r\n\r\n",
    b"$5\r\nab",                        # torn bulk
    b"$99999999999\r\n",                # absurd bulk: 93GB declared
    b"$536870913\r\n",                  # one past the 512MB hard ceiling
    b"*1\r\n$99999999999\r\n",          # absurd bulk inside an array
    b"*99999999\r\n",                   # absurd array header
    b"*-1\r\n",
    b"*1\r\n*1\r\n*1\r\n*1\r\n:1\r\n",  # deep nesting
    b":99999999999999999999999999\r\n",
    b":-\r\n:+\r\n::\r\n",
    b"+ok\r-err\n$\r\n",
    b"\x00" * 64,
    b"*" * 64,
    b"$" * 64 + b"\r\n",
    b"*3\r\n$3\r\nset\r\n$1\r\nk\r\n$1",  # torn command tail
)


def mutate(rng: random.Random, buf: bytes, n: int):
    """n seeded mutants of buf: bit flips, truncations, splices,
    inserts, deletes — every mutant deterministic from rng state."""
    out = []
    for _ in range(n):
        b = bytearray(buf)
        op = rng.randrange(5)
        if not b:
            op = 3
        if op == 0:                       # bit flip(s)
            for _ in range(rng.randrange(1, 4)):
                i = rng.randrange(len(b))
                b[i] ^= 1 << rng.randrange(8)
        elif op == 1:                     # truncate
            b = b[:rng.randrange(len(b))]
        elif op == 2:                     # splice a slice over another
            i, j = sorted(rng.randrange(len(b) + 1) for _ in range(2))
            k = rng.randrange(len(b) + 1)
            b = b[:i] + b[k:k + (j - i)] + b[j:]
        elif op == 3:                     # insert noise
            i = rng.randrange(len(b) + 1)
            noise = bytes(rng.randrange(256)
                          for _ in range(rng.randrange(1, 9)))
            b = b[:i] + noise + b[i:]
        else:                             # delete a run
            i = rng.randrange(len(b))
            b = b[:i] + b[i + rng.randrange(1, 9):]
        out.append(bytes(b))
    return out


class Driver:
    def __init__(self, ext, seed: int, rounds: int):
        self.ext = ext
        self.seed = seed
        self.rounds = rounds
        self.calls = {}

    def _call(self, name, fn, *args):
        self.calls[name] = self.calls.get(name, 0) + 1
        try:
            return fn(*args)
        except Exception:
            return None  # reject path — only sanitizer reports fail

    # ------------------------------------------------------ resp_parse

    def run_resp(self, rand_msg):
        rng = random.Random(self.seed)
        parse = getattr(self.ext, "resp_parse", None)
        if parse is None:
            raise SystemExit("sanitized ext lacks resp_parse")

        def drive(buf: bytes):
            self._call("resp_parse", parse, buf, 0, *CLASSES, 1024,
                       MAX_BULK)
            # partial-frame handling: a random prefix, and a resume
            # from a random interior position
            if buf:
                self._call("resp_parse", parse, buf[:rng.randrange(len(buf))],
                           0, *CLASSES, 1024, MAX_BULK)
                self._call("resp_parse", parse, bytearray(buf),
                           rng.randrange(len(buf)), *CLASSES, 1024,
                           MAX_BULK)

        for case in FIXED_CASES:
            drive(case)
            for m in mutate(rng, case, 4):
                drive(m)
        # every prefix of one small composite wire — off-by-one framing
        # arithmetic lives at prefix boundaries
        wire = bytearray()
        for _ in range(6):
            encode_into(wire, rand_msg(rng))
        for k in range(len(wire) + 1):
            self._call("resp_parse", parse, bytes(wire[:k]), 0, *CLASSES,
                       1024, MAX_BULK)
        for _ in range(self.rounds):
            wire = bytearray()
            for _ in range(rng.randrange(1, 8)):
                encode_into(wire, rand_msg(rng))
            wire = bytes(wire)
            drive(wire)
            for m in mutate(rng, wire, 6):
                drive(m)

    # ----------------------------------------------------- intake_scan

    def run_intake(self, rand_command):
        rng = random.Random(self.seed + 1)
        scan = getattr(self.ext, "intake_scan", None)
        if scan is None:
            raise SystemExit("sanitized ext lacks intake_scan")

        def drive(buf: bytes):
            self._call("intake_scan", scan, buf, 0, *CLASSES, MAX_BULK)
            if buf:
                self._call("intake_scan", scan, bytearray(buf),
                           rng.randrange(len(buf)), *CLASSES, MAX_BULK)

        for case in FIXED_CASES:
            drive(case)
        for _ in range(self.rounds):
            wire = bytearray()
            for _ in range(rng.randrange(1, 10)):
                encode_into(wire, rand_command(rng))
            wire = bytes(wire)
            drive(wire)
            for m in mutate(rng, wire, 6):
                drive(m)

    # ------------------------------------------------- wire blob codec

    def run_wire(self):
        rng = random.Random(self.seed + 2)
        pack = getattr(self.ext, "wire_pack_blobs", None)
        unpack = getattr(self.ext, "wire_unpack_blobs", None)
        if pack is None or unpack is None:
            raise SystemExit("sanitized ext lacks wire blob codec")
        for _ in range(self.rounds * 2):
            n = rng.randrange(0, 24)
            items = []
            for _ in range(n):
                r = rng.random()
                if r < 0.15:
                    items.append(None)
                elif r < 0.25:      # decline-path shapes (non-bytes)
                    items.append(rng.choice(("s", 7, b"x" * 70000)))
                else:
                    items.append(bytes(rng.randrange(256) for _ in
                                       range(rng.randrange(0, 300))))
            out = bytearray()
            ok = self._call("wire_pack_blobs", pack, out, items)
            if ok:
                # round-trip, then hostile re-reads of the same bytes:
                # wrong count, interior position, mutated framing
                packed = bytes(out)
                self._call("wire_unpack_blobs", unpack, packed, 0, n)
                self._call("wire_unpack_blobs", unpack, packed, 0, n + 3)
                self._call("wire_unpack_blobs", unpack, packed,
                           rng.randrange(len(packed) + 1), n)
                for m in mutate(rng, packed, 4):
                    self._call("wire_unpack_blobs", unpack, m, 0, n)
            # raw garbage with arbitrary declared counts
            junk = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 64)))
            self._call("wire_unpack_blobs", unpack, junk, 0,
                       rng.randrange(0, 1 << 16))

    # -------------------------------------------------------- aof_scan

    def run_aof(self):
        rng = random.Random(self.seed + 3)
        scan = getattr(self.ext, "aof_scan", None)
        if scan is None:
            raise SystemExit("sanitized ext lacks aof_scan")
        from constdb_tpu.persist.oplog import (_MAX_RECORD, MAGIC,
                                               REC_BATCH, REC_FRAME,
                                               REC_WMARK, OpLog,
                                               _pack_record)
        from constdb_tpu.utils.varint import write_uvarint

        def segment():
            seg = bytearray(MAGIC)
            for _ in range(rng.randrange(1, 10)):
                kind = rng.randrange(4)
                if kind == 0:
                    seg += _pack_record(REC_FRAME, OpLog._frame_payload(
                        rng.randrange(16), rng.randrange(1 << 20),
                        b"set",
                        [Bulk(b"k%d" % rng.randrange(64)),
                         Bulk(bytes(rng.randrange(256) for _ in
                                    range(rng.randrange(0, 24))))]))
                elif kind == 1:
                    payload = bytearray()
                    for v in (rng.randrange(16), rng.randrange(1 << 20),
                              rng.randrange(1 << 20), rng.randrange(64)):
                        write_uvarint(payload, v)
                    payload += bytes(rng.randrange(256) for _ in
                                     range(rng.randrange(0, 120)))
                    seg += _pack_record(REC_BATCH, bytes(payload))
                elif kind == 2:
                    payload = bytearray()
                    write_uvarint(payload, rng.randrange(1 << 20))
                    payload += bytes(rng.randrange(256) for _ in
                                     range(rng.randrange(0, 40)))
                    seg += _pack_record(REC_WMARK, bytes(payload))
                else:  # unknown rtype — must end the valid prefix
                    seg += _pack_record(rng.randrange(4, 256),
                                        b"\x00" * rng.randrange(0, 16))
            return bytes(seg)

        def drive(data: bytes):
            # raw walk, frame-decoding walk, and the raw-args flag
            self._call("aof_scan", scan, data, len(MAGIC), _MAX_RECORD)
            self._call("aof_scan", scan, data, len(MAGIC), _MAX_RECORD,
                       *CLASSES)
            self._call("aof_scan", scan, data, len(MAGIC), _MAX_RECORD,
                       *CLASSES, 1)

        for _ in range(self.rounds):
            seg = segment()
            drive(seg)
            for k in (len(seg) - 1, len(seg) - 5,
                      rng.randrange(len(seg) + 1)):
                drive(seg[:max(0, k)])            # torn tails
            for m in mutate(rng, seg, 6):
                drive(m)
            # crc-valid body with a hostile declared length
            body = b"\x01" + b"z" * 8
            evil = (bytearray(MAGIC)
                    + (1 << 31).to_bytes(4, "little")
                    + zlib.crc32(body).to_bytes(4, "little") + body)
            drive(bytes(evil))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="replay fuzz corpora against the sanitized extension")
    ap.add_argument("--ext", default=os.path.join(
        REPO, "native", "build", "san", "cst_ext.so"))
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--rounds", type=int, default=40,
                    help="random corpus buffers per scanner")
    ns = ap.parse_args(argv)

    if not os.path.exists(ns.ext):
        print(f"fuzz_native: sanitized extension not built: {ns.ext} "
              f"(run `make -C native san`)", file=sys.stderr)
        return 2
    try:
        ext = load_sanitized_ext(ns.ext)
    except ImportError as e:
        print(f"fuzz_native: cannot load {ns.ext}: {e}\n"
              f"hint: the sanitized .so links ASan/UBSan dynamically — "
              f"run under LD_PRELOAD=\"$(g++ -print-file-name=libasan.so)"
              f" $(g++ -print-file-name=libubsan.so)\"", file=sys.stderr)
        return 2

    rand_msg, rand_command = load_fuzz_generators()
    drv = Driver(ext, ns.seed, ns.rounds)
    drv.run_resp(rand_msg)
    drv.run_intake(rand_command)
    drv.run_wire()
    drv.run_aof()
    total = sum(drv.calls.values())
    per = ", ".join(f"{k}={v}" for k, v in sorted(drv.calls.items()))
    print(f"fuzz_native: {total} scanner calls clean under ASan+UBSan "
          f"(seed {ns.seed}: {per})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
