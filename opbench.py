#!/usr/bin/env python
"""One-shot reproducible op-path benchmark: regenerates OPBENCH.md.

Boots a fresh single node as a subprocess, runs the shipped pipelined
GET/SET/INCR workload (constdb_tpu/bin/test.py bench_ops) with a warmup
pass and reports the MEDIAN of N timed runs per op — the build machines
run concurrent load, so medians are the honest capacity estimate the
round-4 "best of 3 by hand" numbers were not.

    python opbench.py [--requests 200000] [--runs 3] [--pipeline 64]
                      [--conns 4] [--no-native] [--update]

`--update` rewrites OPBENCH.md with the measured table; without it the
table only prints.  `--no-native` strips the C extension from the server
AND client (CONSTDB_NO_NATIVE=1) to measure the pure-Python floor.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal
import socket
import statistics
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_port(port: int, timeout: float = 20.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), 0.3).close()
            return
        except OSError:
            time.sleep(0.1)
    raise SystemExit(f"server on port {port} never came up")


def _reset_native_caches() -> None:
    """The native tiers cache their load decision process-wide; A/B passes
    in one process must re-evaluate CONSTDB_NO_NATIVE — otherwise the
    'pure' client pass keeps using the C parser/encoder primed by the
    native pass and the published floor is contaminated."""
    from constdb_tpu.resp import codec
    from constdb_tpu.utils import native_tables
    codec._EXT_CACHE.clear()
    codec._ENC_CACHE.clear()
    native_tables._ext = None


def run(requests: int, runs: int, pipeline: int, conns: int,
        native: bool) -> dict[str, int]:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("CONSTDB_NO_NATIVE", None)
    os.environ.pop("CONSTDB_NO_NATIVE", None)
    if not native:
        env["CONSTDB_NO_NATIVE"] = "1"
        os.environ["CONSTDB_NO_NATIVE"] = "1"
    _reset_native_caches()  # the CLIENT side honors the flag too
    port = _free_port()
    srv = subprocess.Popen(
        [sys.executable, "-m", "constdb_tpu.bin.server", "--port", str(port),
         "--node-id", "1", "--engine", "cpu", "--work-dir", "/tmp",
         "--log-level", "warning"],
        env=env, stderr=subprocess.DEVNULL)
    try:
        _wait_port(port)
        from constdb_tpu.bin.test import bench_ops

        addr = f"127.0.0.1:{port}"
        # warmup: primes allocator, code paths, and the key working set
        asyncio.run(bench_ops(addr, max(10_000, requests // 10),
                              pipeline, conns))
        samples: dict[str, list[int]] = {}
        for _ in range(runs):
            got = asyncio.run(bench_ops(addr, requests, pipeline, conns))
            for op, rate in got.items():
                samples.setdefault(op, []).append(rate)
        return {op: int(statistics.median(v)) for op, v in samples.items()}
    finally:
        srv.send_signal(signal.SIGTERM)
        try:
            srv.wait(10)
        except subprocess.TimeoutExpired:
            srv.kill()


TEMPLATE = """# Op-path throughput (client command path)

Regenerate this file with the committed one-shot harness (fixed workload,
warmup pass, median of {runs} runs — see opbench.py):

```
python opbench.py --requests {requests} --runs {runs} --update
```

Measured against a live single node (CPU engine, one asyncio loop) with
the native C RESP parser + encoder on both the server and client side
(native/resp.cpp; interned small-int replies mirror reference
src/resp.rs:12-27):

| op   | requests | pipeline | conns | ops/sec (median of {runs}) |
|------|----------|----------|-------|----------------------------|
| SET  | {requests:,} | {pipeline} | {conns} | {set:,} |
| GET  | {requests:,} | {pipeline} | {conns} | {get:,} |
| INCR | {requests:,} | {pipeline} | {conns} | {incr:,} |

Pure-Python floor on the same machine/run (CONSTDB_NO_NATIVE=1 strips the
extension from server and client):

| op   | ops/sec (median of {runs}) |
|------|----------------------------|
| SET  | {pset:,} |
| GET  | {pget:,} |
| INCR | {pincr:,} |

Where the remaining time goes (cProfile under this load): with parse and
encode in C, the floor is the command dispatch + asyncio socket plumbing
on the single exec loop — the deliberate single-writer trade documented
in SURVEY.md (the reference spends extra cores on parse threads,
reference README.md:12, src/lib.rs:138-142; this build spends C).
Re-check the profile claim with `python opbench.py --profile`.  Encoder
wire bytes are differentially fuzzed against the pure encoder in
tests/test_native_resp.py.

Update this file whenever the op path changes materially.
"""


async def _profile(requests: int, pipeline: int, conns: int) -> None:
    """Server + client in one process under cProfile: shows WHERE the op
    path spends its time (the evidence behind OPBENCH.md's dispatch-floor
    claim)."""
    import cProfile
    import pstats

    from constdb_tpu.bin.test import bench_ops
    from constdb_tpu.server.io import start_node
    from constdb_tpu.server.node import Node

    app = await start_node(Node(node_id=1), host="127.0.0.1", port=0,
                           work_dir="/tmp")
    prof = cProfile.Profile()
    prof.enable()
    await bench_ops(app.advertised_addr, requests, pipeline, conns)
    prof.disable()
    await app.close()
    pstats.Stats(prof).sort_stats("tottime").print_stats(16)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=200_000)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--pipeline", type=int, default=64)
    ap.add_argument("--conns", type=int, default=4)
    ap.add_argument("--no-native", action="store_true")
    ap.add_argument("--update", action="store_true",
                    help="rewrite OPBENCH.md (runs native AND pure passes)")
    ap.add_argument("--profile", action="store_true",
                    help="cProfile the server under load (in-process) and "
                         "print the top self-time entries")
    ns = ap.parse_args()

    if ns.profile:
        asyncio.run(_profile(ns.requests, ns.pipeline, ns.conns))
        return

    if ns.update:
        print("== native (parser + encoder in C) ==")
        nat = run(ns.requests, ns.runs, ns.pipeline, ns.conns, native=True)
        print("== pure python ==")
        pure = run(ns.requests, ns.runs, ns.pipeline, ns.conns, native=False)
        out = TEMPLATE.format(requests=ns.requests, runs=ns.runs,
                              pipeline=ns.pipeline, conns=ns.conns,
                              set=nat["set"], get=nat["get"],
                              incr=nat["incr"], pset=pure["set"],
                              pget=pure["get"], pincr=pure["incr"])
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "OPBENCH.md")
        with open(path, "w") as f:
            f.write(out)
        print(f"wrote {path}")
        for op in ("set", "get", "incr"):
            print(f"  {op:5s}: native {nat[op]:,}  pure {pure[op]:,}  "
                  f"({nat[op] / max(pure[op], 1):.2f}x)")
    else:
        res = run(ns.requests, ns.runs, ns.pipeline, ns.conns,
                  native=not ns.no_native)
        for op, rate in res.items():
            print(f"  {op:5s}: {rate:,} ops/sec (median of {ns.runs})")


if __name__ == "__main__":
    main()
